"""Pallas TPU kernel: paged prefill (chunked) attention.

The XLA reference path (ops/attention.py) materializes every page of a
sequence's context as a gathered [B, S, KV, D] array per prefill chunk
— HBM traffic proportional to the page-table width regardless of the
real context length. This kernel walks the page list instead, exactly
like the decode kernel (ops/paged_attention_pallas.py), with a chunk
of T query tokens per sequence:

- grid (batch, kv_head); the whole page walk runs *inside* the kernel
  as a STATIC unroll over the page-table width with ``pl.when``
  guards on the row's real chunk count (the round-2 grid-per-page
  design paid a fixed cost per tiny BlockSpec DMA and lost to the
  XLA gather on-chip; a dynamic fori_loop bound hung Mosaic's AOT
  compiler — see ops/paged_attention_pallas.py),
- KV pages live in HBM and are copied in double-buffered bursts of C
  pages via manual async DMAs; pages are stored token-minor
  ([head_dim, page_size]) so the slices are tile-aligned and K needs
  no transpose before the ``q @ k^T`` MXU contraction,
- queries arrive flattened [G*T, D] so both matmuls stay plain 2D MXU
  contractions, zero-padded to true (8, 128) tile multiples — the
  whole-dim block escape hatch the Python lowering rules allow is not
  honored by Mosaic's machine-code pass for small-head models
  (head_dim=64 lowered cross-platform and then failed on chip,
  BENCH_r02), so the wrapper pads rows/head_dim outright and the
  kernel zeroes the matching KV-scratch pad sublanes,
- causal masking is rebuilt in-kernel from a scalar-prefetched per-row
  chunk start: query positions within a prefill chunk are contiguous
  (engine/model_runner.py run_prefill), so ``start + iota`` recovers
  them without shipping a [B, T] positions array through VMEM (a
  (1, T) int32 VMEM block violates Mosaic's (8, 128) tiling rule —
  the round-2 on-chip compile failure, BENCH_r02 ``pallas_error``),
- flash-style online softmax in VMEM scratch across the page walk.

Contract matches ops.attention.paged_attention for contiguous per-row
q_positions (the engine's chunked-prefill shape); parity is tested in
tests/test_pallas_attention.py and compiled lowering is checked by
tests/test_pallas_lowering.py (TPU cross-lowering, no chip needed).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from production_stack_tpu.ops.paged_kv_common import (
    LANE_TILE,
    NEG_INF,
    SUBLANE_TILE,
    cache_alias_map,
    dma_semaphore_shapes,
    hbm_block_spec,
    kv_scratch_shapes,
    make_page_dma,
    pad_page_table,
    pad_query_rows,
    passthrough_out_shapes,
    rewrap_cache_outputs,
    run_page_walk,
    tile_pad,
    unwrap_cache,
    validate_layer_arg,
    zero_pad_sublanes,
)

# Pages per DMA burst (2 x 128-token pages = a 256-token KV tile per
# compute step — prefill scores are [G*T, tile], so a fatter tile
# costs VMEM quadratically while the MXU is already saturated).
_PAGES_PER_CHUNK = 2


def _prefill_kernel(page_table_ref, kv_lens_ref, q_start_ref,
                    layer_ref, q_ref,
                    k_hbm, v_hbm, ks_hbm, vs_hbm, o_ref,
                    m_ref, l_ref, acc_ref,
                    k_scratch, v_scratch, ks_scratch, vs_scratch,
                    sem, ssem, *,
                    page_size: int, pages_per_chunk: int,
                    chunk: int, head_dim: int, head_dim_pad: int,
                    rows_pad: int, max_pages: int,
                    has_layer: bool, quantized: bool):
    # ks_hbm/vs_hbm carry the per-slot f32 dequant scales of an int8
    # cache (ops/quant_kv.py), pre-reshaped by the wrapper to
    # [.., pages, 1, page_size]; None for a full-precision cache.
    b = pl.program_id(0)
    h = pl.program_id(1)
    c = pages_per_chunk
    chunk_tokens = c * page_size
    max_chunks = max_pages // c  # static unroll bound

    kv_len = kv_lens_ref[b]
    q_start = q_start_ref[b]
    num_chunks = (kv_len + chunk_tokens - 1) // chunk_tokens

    issue, wait = make_page_dma(
        b=b, h=h, page_table_ref=page_table_ref, layer_ref=layer_ref,
        k_hbm=k_hbm, v_hbm=v_hbm, ks_hbm=ks_hbm, vs_hbm=vs_hbm,
        k_scratch=k_scratch, v_scratch=v_scratch,
        ks_scratch=ks_scratch, vs_scratch=vs_scratch,
        sem=sem, ssem=ssem, pages_per_chunk=c, page_size=page_size,
        has_layer=has_layer, quantized=quantized,
        dma_sublanes=(head_dim if head_dim_pad != head_dim else None),
    )

    # Padded rows (kv_len == 0 -> num_chunks == 0) must not issue the
    # warmup DMAs: the loop never waits them, and an unwaited DMA
    # leaks its semaphore signal into the next grid step's waits.
    @pl.when(num_chunks > 0)
    def _warmup():
        issue(0, 0)

    m_ref[...] = jnp.full_like(m_ref, NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)
    zero_pad_sublanes(k_scratch, v_scratch, head_dim, head_dim_pad)

    q = q_ref[0, 0].astype(jnp.float32)  # [rows_pad, D_pad]

    # Row r of the flattened queries is (g, t) = (r // T, r % T) whose
    # absolute position is q_start + t (chunk positions contiguous).
    q_pos = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (rows_pad, chunk_tokens), 0
    ) % chunk  # [rows_pad, C*P]

    run_page_walk(
        q=q, kv_len=kv_len, num_chunks=num_chunks,
        max_chunks=max_chunks, chunk_tokens=chunk_tokens,
        head_dim=head_dim, issue=issue, wait=wait,
        k_scratch=k_scratch, v_scratch=v_scratch,
        ks_scratch=ks_scratch, vs_scratch=vs_scratch,
        m_ref=m_ref, l_ref=l_ref, acc_ref=acc_ref,
        # Causal over the chunk's own tokens plus everything cached
        # before it — exactly the ragged mixed-length contract: each
        # row masks independently off its scalar-prefetched start.
        mask_fn=lambda token_pos: ((token_pos <= q_pos)
                                   & (token_pos < kv_len)),
        quantized=quantized,
    )

    denom = jnp.maximum(l_ref[...], 1e-30)
    o_ref[0, 0] = (acc_ref[...] / denom).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_prefill_attention(q: jnp.ndarray, k_cache_layer: jnp.ndarray,
                            v_cache_layer: jnp.ndarray,
                            page_table: jnp.ndarray,
                            q_positions: jnp.ndarray,
                            kv_lens: jnp.ndarray,
                            layer: "jnp.ndarray | int | None" = None,
                            interpret: bool = False) -> jnp.ndarray:
    """Chunked-prefill attention against a sequence's cached pages.

    Args:
      q:           [B, T, num_q_heads, head_dim] (chunk, padded)
      k/v_cache_layer: [num_kv_heads, num_pages, head_dim, page_size],
                   or the full stacked [L, ...] cache with ``layer``
                   given (scalar; reaches the kernel via SMEM prefetch
                   so no per-layer slice is ever materialized)
      page_table:  [B, max_pages] int32 physical page ids
      q_positions: [B, T] int32 absolute positions of the queries;
                   must be contiguous per row (positions[i] =
                   start_i + arange(T)), the engine's chunked-prefill
                   shape — only row starts reach the kernel (SMEM)
      kv_lens:     [B] int32 valid cached tokens (incl. this chunk)
      interpret:   run in interpreter mode (CPU testing)

    Returns [B, T, num_q_heads, head_dim] for the 4D per-layer cache
    form; ``(out, k_cache, v_cache)`` for the stacked 5D form (caches
    pass through the kernel aliased — see paged_decode_attention).
    """
    has_layer = validate_layer_arg(k_cache_layer, layer)
    (quantized, k_data, v_data,
     k_scale, v_scale, scale_shape) = unwrap_cache(
        k_cache_layer, v_cache_layer)
    layer_arr = jnp.asarray(
        [0 if layer is None else layer], jnp.int32)
    b, t, num_q_heads, head_dim = q.shape
    num_kv_heads, _, _, page_size = k_data.shape[-4:]
    group = num_q_heads // num_kv_heads
    c = _PAGES_PER_CHUNK

    page_table, max_pages = pad_page_table(page_table, c)

    # [B, T, KV, G, D] -> [B, KV, G*T, D]: rows of one kv head's
    # queries, flattened so kernel matmuls are 2D, then tile-padded
    # to true (8, 128) multiples. Mosaic's machine-code pass is
    # stricter than the Python lowering rules about whole-dim q/o
    # blocks (the BENCH_r02 small-head failure: head_dim=64 lowered
    # cross-platform and failed on chip), so the wrapper pads and the
    # kernel zeroes the matching KV-scratch sublanes.
    rows = group * t
    rows_pad = max(tile_pad(rows, SUBLANE_TILE), SUBLANE_TILE)
    d_pad = tile_pad(head_dim, LANE_TILE)
    qg = (q.reshape(b, t, num_kv_heads, group, head_dim)
          .transpose(0, 2, 3, 1, 4)
          .reshape(b, num_kv_heads, rows, head_dim))
    qg = pad_query_rows(qg, rows_pad, d_pad)

    # Only the per-row chunk start crosses into the kernel (SMEM
    # scalar prefetch); positions are rebuilt as start + iota.
    q_start = q_positions[:, 0]

    base_kernel = functools.partial(
        _prefill_kernel, page_size=page_size, pages_per_chunk=c,
        chunk=t, head_dim=head_dim, head_dim_pad=d_pad,
        rows_pad=rows_pad, max_pages=max_pages,
        has_layer=has_layer, quantized=quantized,
    )
    n_cache_in = 4 if quantized else 2
    # Stacked-form pass-through cache outputs exist only for the
    # input/output aliasing (see paged_decode_attention); the kernel
    # never touches them, so this adapter strips them (and splices
    # None for the quant-only refs) before the canonical signature.
    n_pass = n_cache_in if has_layer else 0

    def kernel(pt, kl, qs, la, q_ref, *refs):
        cache_in = refs[:n_cache_in]
        o_ref = refs[n_cache_in]
        scratch = refs[n_cache_in + 1 + n_pass:]
        if quantized:
            k, v, ks, vs = cache_in
            (m, l, acc, k_s, v_s, ks_s, vs_s, sem, ssem) = scratch
        else:
            k, v = cache_in
            ks = vs = ks_s = vs_s = ssem = None
            (m, l, acc, k_s, v_s, sem) = scratch
        base_kernel(pt, kl, qs, la, q_ref, k, v, ks, vs, o_ref,
                    m, l, acc, k_s, v_s, ks_s, vs_s, sem, ssem)

    hbm = hbm_block_spec()
    scratch_shapes = [
        pltpu.VMEM((rows_pad, 1), jnp.float32),  # m
        pltpu.VMEM((rows_pad, 1), jnp.float32),  # l
        pltpu.VMEM((rows_pad, d_pad), jnp.float32),  # acc
    ]
    scratch_shapes += kv_scratch_shapes(
        d_pad, c, page_size, k_data.dtype, v_data.dtype, quantized)
    scratch_shapes += dma_semaphore_shapes(c, quantized)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,  # page_table, kv_lens, q_start, layer
        grid=(b, num_kv_heads),
        in_specs=[
            pl.BlockSpec(
                (1, 1, rows_pad, d_pad),
                lambda bi, hi, pt, kl, qs, la: (bi, hi, 0, 0),
            ),
        ] + [hbm] * n_cache_in,
        out_specs=[
            pl.BlockSpec(
                (1, 1, rows_pad, d_pad),
                lambda bi, hi, pt, kl, qs, la: (bi, hi, 0, 0),
            ),
        ] + [hbm] * n_pass,
        scratch_shapes=scratch_shapes,
    )

    out_shape = [jax.ShapeDtypeStruct(
        (b, num_kv_heads, rows_pad, d_pad), q.dtype)]
    operands = [page_table, kv_lens, q_start, layer_arr, qg,
                k_data, v_data]
    if quantized:
        operands += [k_scale, v_scale]
    if has_layer:
        out_shape += passthrough_out_shapes(
            k_data, v_data, k_scale, v_scale, quantized)
    aliases = cache_alias_map(4, n_cache_in, has_layer)
    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid_spec=grid_spec,
        input_output_aliases=aliases,
        interpret=interpret,
    )(*operands)
    out = (res[0][:, :, :rows, :head_dim]
           .reshape(b, num_kv_heads, group, t, head_dim)
           .transpose(0, 3, 1, 2, 4)
           .reshape(b, t, num_q_heads, head_dim))
    if has_layer:
        kc, vc = rewrap_cache_outputs(res, scale_shape, quantized)
        return out, kc, vc
    return out
