"""Pallas TPU kernel: paged prefill (chunked) attention.

The XLA reference path (ops/attention.py) materializes every page of a
sequence's context as a gathered [B, S, KV, D] array per prefill chunk
— HBM traffic proportional to the page-table width regardless of the
real context length. This kernel walks the page list instead, exactly
like the decode kernel (ops/paged_attention_pallas.py), with a chunk of
T query tokens per sequence:

- grid (batch, kv_head, pages); one KV page DMA'd per step via the
  scalar-prefetched page table,
- queries arrive flattened [G*T, D] so both matmuls stay plain 2D MXU
  contractions (Mosaic's supported form),
- causal masking: a [T, P] position mask (query positions are a VMEM
  input) broadcast over the G query groups,
- flash-style online softmax in VMEM scratch across the page walk.

Contract matches ops.attention.paged_attention for any T; parity is
tested in tests/test_pallas_attention.py (interpret mode on CPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _prefill_kernel(page_table_ref, kv_lens_ref, q_ref, pos_ref,
                    k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                    page_size: int, group: int, chunk: int):
    b = pl.program_id(0)
    p = pl.program_id(2)
    num_page_steps = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0].astype(jnp.float32)  # [G*T, D]
    k = k_ref[0, 0].astype(jnp.float32)  # [P, D]
    v = v_ref[0, 0].astype(jnp.float32)  # [P, D]
    head_dim = q.shape[-1]

    scale = 1.0 / (head_dim ** 0.5)
    scores = jax.lax.dot_general(
        q, k,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [G*T, P]

    # Causal + length mask, built at [T, P] and broadcast over groups.
    q_pos = pos_ref[0]  # [T] int32 absolute positions
    kv_len = kv_lens_ref[b]
    token_pos = p * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (chunk, page_size), 1
    )  # [T, P]
    mask_tp = (token_pos <= q_pos[:, None]) & (token_pos < kv_len)
    mask = jnp.broadcast_to(
        mask_tp[None], (group, chunk, page_size)
    ).reshape(group * chunk, page_size)
    scores = jnp.where(mask, scores, NEG_INF)

    # Online softmax update.
    m_prev = m_ref[...]  # [G*T, 1]
    m_new = jnp.maximum(
        m_prev, jnp.max(scores, axis=-1, keepdims=True)
    )
    alpha = jnp.exp(m_prev - m_new)
    probs = jnp.exp(scores - m_new)
    l_ref[...] = l_ref[...] * alpha + jnp.sum(
        probs, axis=-1, keepdims=True
    )
    pv = jax.lax.dot_general(
        probs, v,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [G*T, D]
    acc_ref[...] = acc_ref[...] * alpha + pv
    m_ref[...] = m_new

    @pl.when(p == num_page_steps - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / denom).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_prefill_attention(q: jnp.ndarray, k_cache_layer: jnp.ndarray,
                            v_cache_layer: jnp.ndarray,
                            page_table: jnp.ndarray,
                            q_positions: jnp.ndarray,
                            kv_lens: jnp.ndarray,
                            interpret: bool = False) -> jnp.ndarray:
    """Chunked-prefill attention against a sequence's cached pages.

    Args:
      q:           [B, T, num_q_heads, head_dim] (chunk, padded)
      k/v_cache_layer: [num_kv_heads, num_pages, page_size, head_dim]
      page_table:  [B, max_pages] int32 physical page ids
      q_positions: [B, T] int32 absolute positions of the queries
      kv_lens:     [B] int32 valid cached tokens (incl. this chunk)
      interpret:   run in interpreter mode (CPU testing)

    Returns [B, T, num_q_heads, head_dim].
    """
    b, t, num_q_heads, head_dim = q.shape
    num_kv_heads, _, page_size, _ = k_cache_layer.shape
    max_pages = page_table.shape[1]
    group = num_q_heads // num_kv_heads

    # [B, T, KV, G, D] -> [B, KV, G*T, D]: rows of one kv head's
    # queries, flattened so kernel matmuls are 2D.
    qg = (q.reshape(b, t, num_kv_heads, group, head_dim)
          .transpose(0, 2, 3, 1, 4)
          .reshape(b, num_kv_heads, group * t, head_dim))

    kernel = functools.partial(
        _prefill_kernel, page_size=page_size, group=group, chunk=t,
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page_table, kv_lens
        grid=(b, num_kv_heads, max_pages),
        in_specs=[
            pl.BlockSpec(
                (1, 1, group * t, head_dim),
                lambda bi, hi, pi, pt, kl: (bi, hi, 0, 0),
            ),
            # Query positions for this sequence's chunk.
            pl.BlockSpec(
                (1, t),
                lambda bi, hi, pi, pt, kl: (bi, 0),
            ),
            pl.BlockSpec(
                (1, 1, page_size, head_dim),
                lambda bi, hi, pi, pt, kl: (hi, pt[bi, pi], 0, 0),
            ),
            pl.BlockSpec(
                (1, 1, page_size, head_dim),
                lambda bi, hi, pi, pt, kl: (hi, pt[bi, pi], 0, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, group * t, head_dim),
            lambda bi, hi, pi, pt, kl: (bi, hi, 0, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((group * t, 1), jnp.float32),  # m
            pltpu.VMEM((group * t, 1), jnp.float32),  # l
            pltpu.VMEM((group * t, head_dim), jnp.float32),  # acc
        ],
    )

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(
            (b, num_kv_heads, group * t, head_dim), q.dtype
        ),
        grid_spec=grid_spec,
        interpret=interpret,
    )(page_table, kv_lens, qg, q_positions, k_cache_layer,
      v_cache_layer)
    return (out.reshape(b, num_kv_heads, group, t, head_dim)
            .transpose(0, 3, 1, 2, 4)
            .reshape(b, t, num_q_heads, head_dim))
