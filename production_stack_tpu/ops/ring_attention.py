"""Ring attention: exact causal attention over a sequence-sharded ring.

Long-context strategy for this stack. The reference delegates sequence
length entirely to the engine (`maxModelLen`/chunked-prefill flags passed
through to vLLM, reference helm/templates/deployment-vllm-multi.yaml:69-79)
and has no sequence/context parallelism anywhere; here long context is a
first-class mesh axis (``sp``): every device holds a ``T/n`` slice of the
sequence, K/V blocks rotate around the ring with ``lax.ppermute`` over
ICI, and attention accumulates with an online (flash-style) softmax so the
full [T, T] score matrix never materializes. Compute on each hop overlaps
XLA's async collective-permute, so ICI latency hides behind the block
matmuls (the scaling-book ring-attention recipe).

This module is written to run *inside* ``shard_map`` — all collectives are
explicit (``ppermute`` / ``axis_index``) and everything else is local
block math that XLA tiles onto the MXU.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from production_stack_tpu.utils.compat import shard_map

NEG_INF = -1e30


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str, causal: bool = True) -> jnp.ndarray:
    """Exact attention with q/k/v sharded along the sequence dimension.

    Must be called inside ``shard_map`` with sequence dim mapped to mesh
    axis ``axis_name``. Grouped-query attention is supported (num q heads
    a multiple of num kv heads).

    Args:
      q: [B, T_local, num_q_heads, head_dim] local query shard.
      k: [B, T_local, num_kv_heads, head_dim] local key shard.
      v: [B, T_local, num_kv_heads, head_dim] local value shard.
      axis_name: mesh axis the sequence is sharded over.
      causal: apply a global causal mask (positions are global:
        shard i covers [i*T_local, (i+1)*T_local)).

    Returns [B, T_local, num_q_heads, head_dim], the local output shard.
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, t, hq, d = q.shape
    hkv = k.shape[2]
    group = hq // hkv
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))

    qg = q.astype(jnp.float32).reshape(b, t, hkv, group, d)
    q_pos = idx * t + jnp.arange(t)  # global positions of local queries

    perm = [(j, (j + 1) % n) for j in range(n)]

    def block(carry, step):
        k_blk, v_blk, m, l, o = carry
        src = (idx - step) % n  # which shard's K/V we hold this hop
        kv_pos = src * t + jnp.arange(t)

        # [B, kv, group, Tq, Tkv]
        scores = jnp.einsum(
            "btkgd,bskd->bkgts", qg, k_blk.astype(jnp.float32)
        ) * scale
        if causal:
            mask = kv_pos[None, :] <= q_pos[:, None]  # [Tq, Tkv]
            scores = jnp.where(mask[None, None, None], scores, NEG_INF)

        blk_max = jnp.max(scores, axis=-1)  # [B, kv, g, Tq]
        new_m = jnp.maximum(m, blk_max)
        # Guard: a fully-masked block keeps new_m finite via the old m;
        # on the very first hop the diagonal block is never fully masked.
        correction = jnp.exp(m - new_m)
        p = jnp.exp(scores - new_m[..., None])  # [B, kv, g, Tq, Tkv]
        new_l = l * correction + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bkgts,bskd->bkgtd", p,
                        v_blk.astype(jnp.float32))
        new_o = o * correction[..., None] + pv

        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_next, v_next, new_m, new_l, new_o), None

    m0 = jnp.full((b, hkv, group, t), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hkv, group, t), jnp.float32)
    o0 = jnp.zeros((b, hkv, group, t, d), jnp.float32)
    (_, _, m, l, o), _ = jax.lax.scan(
        block, (k, v, m0, l0, o0), jnp.arange(n)
    )

    out = o / l[..., None]  # [B, kv, g, Tq, d]
    out = jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(b, t, hq, d)
    return out.astype(q.dtype)


def ring_attention_sharded(q: jnp.ndarray, k: jnp.ndarray,
                           v: jnp.ndarray, mesh,
                           sp_axis: str = "sp",
                           causal: bool = True) -> jnp.ndarray:
    """Convenience wrapper: shard_map ``ring_attention`` over ``sp_axis``.

    q/k/v are global [B, T, H, D] arrays; T must divide evenly by the
    size of the ``sp`` axis. Batch/head dims stay replicated here — for
    combined dp x sp x tp, call ``ring_attention`` inside your own
    shard_map (see parallel/context.py).
    """
    from jax.sharding import PartitionSpec as P
    spec = P(None, sp_axis, None, None)
    fn = shard_map(
        partial(ring_attention, axis_name=sp_axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
