"""Quantized paged-KV container: int8 pages + per-slot f32 scales.

Layout (docs/kv_quantization.md): the ``data`` leaf keeps the exact
page layout of a full-precision cache — ``[kv_heads, num_pages,
head_dim, page_size]`` per layer, or stacked with a leading layer
axis — but stored as int8. The ``scale`` leaf drops the head_dim axis:
one f32 symmetric scale per (layer, kv_head, page, page_slot), i.e.
``[kv_heads, num_pages, page_size]`` / ``[L, kv, pages, page_size]``.
Per-slot granularity makes incremental page writes (decode commit,
spec-decode eager drafts, deferred-burst flush) exact: writing one
token slot never rescales a neighbour's values.

``QuantKV`` is deliberately NOT a tuple/NamedTuple: the runner and
models distinguish per-layer caches from stacked ones with
``isinstance(cache, (list, tuple))``, so the container must read as a
single array-like object. It delegates ``ndim``/``shape``/``dtype`` to
the data leaf so rank checks and ``shape[-1]`` (page_size) probes work
unchanged, and ``__getitem__`` applies the same index to both leaves —
valid for every index the stack uses (``[layer]``, ``[:, page_table]``,
``[:, page_id]``, ``[:, :, page_id]``), all of which touch only the
leading ``[L?, kv, pages]`` axes the two leaves share.

Registered as a pytree so it flows through jit/donation/device_get and
``jax.ShapeDtypeStruct`` lowering probes for free.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Mirrors quantize_weight (engine/quantization.py): symmetric int8
# with an amax/127 scale, floored so all-zero slots stay invertible.
_QMAX = 127.0
_SCALE_FLOOR = 1e-8


@jax.tree_util.register_pytree_node_class
class QuantKV:
    """int8 KV pages plus their per-(head, page, slot) f32 scales."""

    __slots__ = ("data", "scale")

    def __init__(self, data, scale):
        self.data = data
        self.scale = scale

    # -- pytree protocol ------------------------------------------------
    def tree_flatten(self):
        return (self.data, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # -- array-like façade (delegates to the data leaf) -----------------
    @property
    def ndim(self):
        return self.data.ndim

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    def __getitem__(self, idx):
        # Same index on both leaves: callers only index the shared
        # leading [layer?, kv_head, page] axes (asserted by use sites,
        # not here — this stays trace-safe under jit).
        return QuantKV(self.data[idx], self.scale[idx])

    def __repr__(self):
        return (f"QuantKV(data={getattr(self.data, 'shape', self.data)},"
                f" scale={getattr(self.scale, 'shape', self.scale)})")


def quantize_kv(x: jnp.ndarray):
    """Quantize new KV rows ``[..., head_dim]`` to (int8, f32 scale).

    The scale is an amax over the trailing head_dim axis — one scale
    per (token, kv_head) row, matching the per-slot scale layout of
    the cache. Returns ``(q, scale)`` with ``q`` int8 shaped like
    ``x`` and ``scale`` f32 shaped ``x.shape[:-1]``.
    """
    x32 = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(x32), axis=-1) / _QMAX
    scale = jnp.maximum(scale, _SCALE_FLOOR)
    q = jnp.clip(jnp.round(x32 / scale[..., None]), -_QMAX, _QMAX)
    return q.astype(jnp.int8), scale


def quant_cache_zeros(shape, scale_dtype=jnp.float32):
    """Fresh all-zero quantized cache for page layout ``shape`` =
    ``[..., num_pages, head_dim, page_size]``."""
    scale_shape = shape[:-2] + (shape[-1],)
    return QuantKV(jnp.zeros(shape, jnp.int8),
                   jnp.zeros(scale_shape, scale_dtype))


def quant_cache_struct(shape, scale_dtype=jnp.float32):
    """ShapeDtypeStruct twin of :func:`quant_cache_zeros` for
    lowering probes (the runner's pallas feasibility checks)."""
    scale_shape = shape[:-2] + (shape[-1],)
    return QuantKV(jax.ShapeDtypeStruct(shape, jnp.int8),
                   jax.ShapeDtypeStruct(scale_shape, scale_dtype))
