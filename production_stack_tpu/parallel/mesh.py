"""Device mesh + sharding layout for tensor/data parallel serving.

The reference delegates tensor parallelism to vLLM/NCCL and provisions
/dev/shm for it (deployment-vllm-multi.yaml:84-87,226-233). Here TP is a
first-class mesh axis: weights carry NamedShardings over the ``tp`` axis
(attention heads / MLP columns), the KV cache shards its kv-head dim,
and XLA/GSPMD inserts the ICI collectives — we write layouts, not
communication code. ``dp`` is the replica axis for batch sharding.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from production_stack_tpu.engine.config import ModelConfig


def build_mesh(tensor_parallel_size: int = 1,
               data_parallel_size: int = 1,
               pipeline_parallel_size: int = 1,
               context_parallel_size: int = 1,
               devices=None,
               num_slices: int = 0,
               placement=None) -> Mesh:
    """(dp, pp, sp, tp) mesh. tp is innermost so tensor-parallel
    collectives ride adjacent ICI links; sp ring hops are next (ring
    attention's ppermute neighbours stay adjacent); pp stage hops
    cross the slowest dimension (or DCN on multi-slice).

    Thin wrapper over the declarative ``MeshPlan``
    (parallel/topology.py): the device grid is laid out slice-major
    over the DISCOVERED topology and the plan is validated against it
    — tp straddling a slice boundary is a config-time ValueError here,
    not a silent DCN-slow collective at first dispatch."""
    from production_stack_tpu.parallel.topology import (
        MeshPlan,
        discover_topology,
    )
    topology = discover_topology(devices, num_slices=num_slices)
    plan = MeshPlan(
        tp=tensor_parallel_size, dp=data_parallel_size,
        pp=pipeline_parallel_size, sp=context_parallel_size,
        **({"placement": placement} if placement else {}))
    if plan.num_devices > topology.num_devices:
        raise ValueError(
            f"Mesh needs {plan.num_devices} devices, "
            f"have {topology.num_devices}"
        )
    return plan.build(topology)


# PartitionSpecs per parameter name. Layer-stacked params have a leading
# L dim (never sharded). Column-parallel projections shard their output
# dim; row-parallel shard their input dim; GSPMD places the psum.
_LLAMA_SPECS: Dict[str, P] = {
    "embed": P(None, None),
    "final_norm": P(None),
    "attn_norm": P(None, None),
    "wq": P(None, None, "tp"),
    "wk": P(None, None, "tp"),
    "wv": P(None, None, "tp"),
    "wo": P(None, "tp", None),
    "mlp_norm": P(None, None),
    "w_gate": P(None, None, "tp"),
    "w_up": P(None, None, "tp"),
    "w_down": P(None, "tp", None),
    "lm_head": P(None, "tp"),
    # Qwen2-style attention biases follow their projections' columns.
    "bq": P(None, "tp"),
    "bk": P(None, "tp"),
    "bv": P(None, "tp"),
}

_OPT_SPECS: Dict[str, P] = {
    "embed": P(None, None),
    "pos_embed": P(None, None),
    "final_norm_w": P(None), "final_norm_b": P(None),
    "attn_norm_w": P(None, None), "attn_norm_b": P(None, None),
    "wq": P(None, None, "tp"), "bq": P(None, "tp"),
    "wk": P(None, None, "tp"), "bk": P(None, "tp"),
    "wv": P(None, None, "tp"), "bv": P(None, "tp"),
    "wo": P(None, "tp", None), "bo": P(None, None),
    "mlp_norm_w": P(None, None), "mlp_norm_b": P(None, None),
    "fc1": P(None, None, "tp"), "fc1_b": P(None, "tp"),
    "fc2": P(None, "tp", None), "fc2_b": P(None, None),
}


# Mixtral: attention shards like llama; the EXPERT axis of the MoE
# weights shards over 'tp' — expert parallelism (each device computes
# its local experts; GSPMD inserts the combine psum). The router gate
# is replicated.
_MIXTRAL_SPECS: Dict[str, P] = {
    "embed": P(None, None),
    "final_norm": P(None),
    "attn_norm": P(None, None),
    "wq": P(None, None, "tp"),
    "wk": P(None, None, "tp"),
    "wv": P(None, None, "tp"),
    "wo": P(None, "tp", None),
    "mlp_norm": P(None, None),
    "moe_gate": P(None, None, None),
    "w_gate": P(None, "tp", None, None),
    "w_up": P(None, "tp", None, None),
    "w_down": P(None, "tp", None, None),
    "lm_head": P(None, "tp"),
}


def param_specs(config: ModelConfig) -> Dict[str, P]:
    if config.architecture in ("opt", "gpt2"):
        return dict(_OPT_SPECS)
    if config.architecture == "mixtral":
        return dict(_MIXTRAL_SPECS)
    return dict(_LLAMA_SPECS)


def _pp_size(mesh: Optional[Mesh]) -> int:
    if mesh is None or "pp" not in mesh.axis_names:
        return 1
    return mesh.shape["pp"]


# The canonical axis vocabulary (parallel/topology.py AXIS_ORDER):
# _on_mesh may legally drop one of these when a caller-built mesh
# carries a subset, but anything else in a spec is a typo.
_KNOWN_AXES = ("dp", "pp", "sp", "tp")


def _on_mesh(spec: P, mesh: Mesh) -> P:
    """Drop KNOWN axis names the mesh doesn't carry (a caller-built
    mesh may have only a subset of build_mesh's four axes — e.g. an
    ('sp',) mesh for context-parallel prefill): absent known axes mean
    replicated. An axis name that is neither on the mesh nor in the
    canonical vocabulary is a spec typo — silently replicating it
    would shard nothing and waste HBM quietly, so fail loudly."""
    def keep(a):
        names = a if isinstance(a, (tuple, list)) else (a,)
        for name in names:
            if (name is not None and name not in mesh.axis_names
                    and name not in _KNOWN_AXES):
                raise ValueError(
                    f"PartitionSpec axis {name!r} is neither a mesh "
                    f"axis {tuple(mesh.axis_names)} nor a known axis "
                    f"{_KNOWN_AXES} — misspelled spec?")
        return a if all(n in mesh.axis_names for n in names) else None
    return P(*(keep(a) for a in spec))


def shard_params(params: Dict[str, jax.Array], config: ModelConfig,
                 mesh: Optional[Mesh]) -> Dict[str, jax.Array]:
    if mesh is None:
        return params
    specs = param_specs(config)
    if _pp_size(mesh) > 1:
        # Pipeline stages own contiguous layer blocks: layer-stacked
        # params shard their leading L axis over 'pp'
        # (parallel/pipeline_serving.py consumes these shards).
        from production_stack_tpu.models.llama import _layer_param_names
        for name in _layer_param_names(config):
            if name in specs:
                specs[name] = P("pp", *specs[name][1:])

    def place(name, value):
        spec = _on_mesh(specs.get(name, P()), mesh)
        if isinstance(value, tuple):
            # int8 (weight [L, in, out], scale [L, out]) pair: the
            # scale follows the weight's layer + output-channel axes.
            w, scale = value
            scale_spec = (P(spec[0], spec[2])
                          if len(spec) == 3 else P())
            return (
                jax.device_put(w, NamedSharding(mesh, spec)),
                jax.device_put(scale, NamedSharding(mesh, scale_spec)),
            )
        return jax.device_put(value, NamedSharding(mesh, spec))

    return {name: place(name, value)
            for name, value in params.items()}


def cache_spec(mesh: Optional[Mesh] = None) -> P:
    """KV cache [L, kv_heads, pages, head_dim, page_size]: shard heads
    over tp; with pipeline parallelism each stage also owns its own
    layers' pages (L over pp)."""
    spec = (P("pp", "tp", None, None, None) if _pp_size(mesh) > 1
            else P(None, "tp", None, None, None))
    return spec if mesh is None else _on_mesh(spec, mesh)


def shard_cache(cache, mesh: Optional[Mesh]):
    if mesh is None:
        return cache
    from production_stack_tpu.ops.quant_kv import QuantKV
    if isinstance(cache, QuantKV):
        # int8 pages + per-slot scales: data shards like a full-precision
        # cache; the scale tensor lacks the head_dim axis, so its spec
        # drops that (always-replicated) entry.
        if cache.data.ndim == 4:
            data_spec = _on_mesh(P("tp", None, None, None), mesh)
            scale_spec = _on_mesh(P("tp", None, None), mesh)
        else:
            data_spec = cache_spec(mesh)
            scale_spec = P(*data_spec[:3], data_spec[4])
        return QuantKV(
            jax.device_put(cache.data, NamedSharding(mesh, data_spec)),
            jax.device_put(cache.scale,
                           NamedSharding(mesh, scale_spec)))
    if cache.ndim == 4:
        # Per-layer buffer [kv_heads, pages, head_dim, page_size]
        # (CacheConfig.cache_layout='per_layer'): heads over tp; no L
        # axis, so pp cannot shard it (the model runner rejects that
        # combination).
        return jax.device_put(
            cache, NamedSharding(
                mesh, _on_mesh(P("tp", None, None, None), mesh)))
    return jax.device_put(cache, NamedSharding(mesh, cache_spec(mesh)))


def replicated(mesh: Optional[Mesh]):
    if mesh is None:
        return None
    return NamedSharding(mesh, P())
