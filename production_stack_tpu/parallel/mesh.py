"""Device mesh + sharding layout for tensor/data parallel serving.

The reference delegates tensor parallelism to vLLM/NCCL and provisions
/dev/shm for it (deployment-vllm-multi.yaml:84-87,226-233). Here TP is a
first-class mesh axis: weights carry NamedShardings over the ``tp`` axis
(attention heads / MLP columns), the KV cache shards its kv-head dim,
and XLA/GSPMD inserts the ICI collectives — we write layouts, not
communication code. ``dp`` is the replica axis for batch sharding.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from production_stack_tpu.engine.config import ModelConfig


def build_mesh(tensor_parallel_size: int = 1,
               data_parallel_size: int = 1,
               pipeline_parallel_size: int = 1,
               context_parallel_size: int = 1,
               devices=None) -> Mesh:
    """(dp, pp, sp, tp) mesh. tp is innermost so tensor-parallel
    collectives ride adjacent ICI links; sp ring hops are next (ring
    attention's ppermute neighbours stay adjacent); pp stage hops
    cross the slowest dimension (or DCN on multi-slice)."""
    devices = devices if devices is not None else jax.devices()
    needed = (tensor_parallel_size * data_parallel_size
              * pipeline_parallel_size * context_parallel_size)
    if len(devices) < needed:
        raise ValueError(
            f"Mesh needs {needed} devices, have {len(devices)}"
        )
    grid = np.asarray(devices[:needed]).reshape(
        data_parallel_size, pipeline_parallel_size,
        context_parallel_size, tensor_parallel_size
    )
    return Mesh(grid, axis_names=("dp", "pp", "sp", "tp"))


# PartitionSpecs per parameter name. Layer-stacked params have a leading
# L dim (never sharded). Column-parallel projections shard their output
# dim; row-parallel shard their input dim; GSPMD places the psum.
_LLAMA_SPECS: Dict[str, P] = {
    "embed": P(None, None),
    "final_norm": P(None),
    "attn_norm": P(None, None),
    "wq": P(None, None, "tp"),
    "wk": P(None, None, "tp"),
    "wv": P(None, None, "tp"),
    "wo": P(None, "tp", None),
    "mlp_norm": P(None, None),
    "w_gate": P(None, None, "tp"),
    "w_up": P(None, None, "tp"),
    "w_down": P(None, "tp", None),
    "lm_head": P(None, "tp"),
    # Qwen2-style attention biases follow their projections' columns.
    "bq": P(None, "tp"),
    "bk": P(None, "tp"),
    "bv": P(None, "tp"),
}

_OPT_SPECS: Dict[str, P] = {
    "embed": P(None, None),
    "pos_embed": P(None, None),
    "final_norm_w": P(None), "final_norm_b": P(None),
    "attn_norm_w": P(None, None), "attn_norm_b": P(None, None),
    "wq": P(None, None, "tp"), "bq": P(None, "tp"),
    "wk": P(None, None, "tp"), "bk": P(None, "tp"),
    "wv": P(None, None, "tp"), "bv": P(None, "tp"),
    "wo": P(None, "tp", None), "bo": P(None, None),
    "mlp_norm_w": P(None, None), "mlp_norm_b": P(None, None),
    "fc1": P(None, None, "tp"), "fc1_b": P(None, "tp"),
    "fc2": P(None, "tp", None), "fc2_b": P(None, None),
}


# Mixtral: attention shards like llama; the EXPERT axis of the MoE
# weights shards over 'tp' — expert parallelism (each device computes
# its local experts; GSPMD inserts the combine psum). The router gate
# is replicated.
_MIXTRAL_SPECS: Dict[str, P] = {
    "embed": P(None, None),
    "final_norm": P(None),
    "attn_norm": P(None, None),
    "wq": P(None, None, "tp"),
    "wk": P(None, None, "tp"),
    "wv": P(None, None, "tp"),
    "wo": P(None, "tp", None),
    "mlp_norm": P(None, None),
    "moe_gate": P(None, None, None),
    "w_gate": P(None, "tp", None, None),
    "w_up": P(None, "tp", None, None),
    "w_down": P(None, "tp", None, None),
    "lm_head": P(None, "tp"),
}


def param_specs(config: ModelConfig) -> Dict[str, P]:
    if config.architecture in ("opt", "gpt2"):
        return dict(_OPT_SPECS)
    if config.architecture == "mixtral":
        return dict(_MIXTRAL_SPECS)
    return dict(_LLAMA_SPECS)


def _pp_size(mesh: Optional[Mesh]) -> int:
    if mesh is None or "pp" not in mesh.axis_names:
        return 1
    return mesh.shape["pp"]


def _on_mesh(spec: P, mesh: Mesh) -> P:
    """Drop axis names the mesh doesn't carry (a caller-built mesh may
    have only a subset of build_mesh's four axes — e.g. an ('sp',)
    mesh for context-parallel prefill): absent axes mean replicated."""
    return P(*(a if a in mesh.axis_names else None for a in spec))


def shard_params(params: Dict[str, jax.Array], config: ModelConfig,
                 mesh: Optional[Mesh]) -> Dict[str, jax.Array]:
    if mesh is None:
        return params
    specs = param_specs(config)
    if _pp_size(mesh) > 1:
        # Pipeline stages own contiguous layer blocks: layer-stacked
        # params shard their leading L axis over 'pp'
        # (parallel/pipeline_serving.py consumes these shards).
        from production_stack_tpu.models.llama import _layer_param_names
        for name in _layer_param_names(config):
            if name in specs:
                specs[name] = P("pp", *specs[name][1:])

    def place(name, value):
        spec = _on_mesh(specs.get(name, P()), mesh)
        if isinstance(value, tuple):
            # int8 (weight [L, in, out], scale [L, out]) pair: the
            # scale follows the weight's layer + output-channel axes.
            w, scale = value
            scale_spec = (P(spec[0], spec[2])
                          if len(spec) == 3 else P())
            return (
                jax.device_put(w, NamedSharding(mesh, spec)),
                jax.device_put(scale, NamedSharding(mesh, scale_spec)),
            )
        return jax.device_put(value, NamedSharding(mesh, spec))

    return {name: place(name, value)
            for name, value in params.items()}


def cache_spec(mesh: Optional[Mesh] = None) -> P:
    """KV cache [L, kv_heads, pages, head_dim, page_size]: shard heads
    over tp; with pipeline parallelism each stage also owns its own
    layers' pages (L over pp)."""
    spec = (P("pp", "tp", None, None, None) if _pp_size(mesh) > 1
            else P(None, "tp", None, None, None))
    return spec if mesh is None else _on_mesh(spec, mesh)


def shard_cache(cache, mesh: Optional[Mesh]):
    if mesh is None:
        return cache
    from production_stack_tpu.ops.quant_kv import QuantKV
    if isinstance(cache, QuantKV):
        # int8 pages + per-slot scales: data shards like a full-precision
        # cache; the scale tensor lacks the head_dim axis, so its spec
        # drops that (always-replicated) entry.
        if cache.data.ndim == 4:
            data_spec = _on_mesh(P("tp", None, None, None), mesh)
            scale_spec = _on_mesh(P("tp", None, None), mesh)
        else:
            data_spec = cache_spec(mesh)
            scale_spec = P(*data_spec[:3], data_spec[4])
        return QuantKV(
            jax.device_put(cache.data, NamedSharding(mesh, data_spec)),
            jax.device_put(cache.scale,
                           NamedSharding(mesh, scale_spec)))
    if cache.ndim == 4:
        # Per-layer buffer [kv_heads, pages, head_dim, page_size]
        # (CacheConfig.cache_layout='per_layer'): heads over tp; no L
        # axis, so pp cannot shard it (the model runner rejects that
        # combination).
        return jax.device_put(
            cache, NamedSharding(
                mesh, _on_mesh(P("tp", None, None, None), mesh)))
    return jax.device_put(cache, NamedSharding(mesh, cache_spec(mesh)))


def replicated(mesh: Optional[Mesh]):
    if mesh is None:
        return None
    return NamedSharding(mesh, P())
