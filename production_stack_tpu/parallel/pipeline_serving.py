"""Pipeline-parallel SERVING forward: paged-KV layer stages over ``pp``.

The reference stack exposes no pipeline parallelism (SURVEY.md §2.6 —
vLLM TP only via --tensor-parallel-size pass-through); this is the
TPU-native extension that serves models deeper than one chip/slice's
HBM. Unlike ``parallel/pipeline.py`` (a dense training-style forward),
this implements the ENGINE's forward contract — paged KV cache writes,
chunked prefill, decode — so ``--pipeline-parallel-size N`` is a real
serving flag (engine/server.py).

Design (idiomatic JAX, static shapes):
- Layer-stacked params and the KV caches shard their leading L axis
  over the ``pp`` mesh axis; each stage owns L/S layers and those
  layers' KV pages. Embedding/head replicate.
- pp composes with tp (round-2 gap): within a stage, projections are
  column/row-sharded over the ``tp`` mesh axis exactly as the plain
  TP path (parallel/mesh.py param_specs) places them; the body runs
  head-local attention (the KV cache shards its kv-head axis) and
  psums the row-parallel projections over ``tp``.
- One ``shard_map`` body runs a static tick loop (M microbatches over
  the batch rows, S stages, M+S-1 ticks). At tick i, stage s runs its
  local layer scan on microbatch i-s; activations hop stage-to-stage
  with ``ppermute`` over ICI/DCN.
- The batch is padded to a multiple of S so M == S always (round-2
  weakness: batch % stages != 0 silently degraded to M=1, a pure
  fill/drain bubble); padded rows carry valid=False so their KV
  writes land on the trash page.
- Bubble ticks compute on don't-care data; their KV writes are masked
  via the ``valid`` mask, which ``ops.attention.write_to_pages``
  redirects to the trash page (page 0) — no cache corruption, no
  dynamic shapes.
- The final hidden states (NOT logits: H << vocab, 16x less traffic)
  are returned to every stage with one masked psum; each stage then
  computes the logits locally (all-gathering over ``tp`` when the LM
  head is column-sharded). This replaces the training pipeline's
  full-activation psum the round-1 review flagged.

Families: the llama body covers llama/mistral/qwen2; gpt2 has its own
layer body (layer_norm + learned positions + gelu — round-2 gap:
pp was llama-only).

Ragged unified step (docs/unified_step.md, docs/parallelism.md): the
forward is shape-generic in T, so the unified [R, W] mixed block and
the spec-verify span ride the SAME staged body — ragged rows become
microbatches and the per-row descriptor triple (kv_lens, last_index
via positions/valid, draft spans) reshapes into the per-tick
microbatch views, threading through every ppermute handoff
unchanged. QuantKV int8 caches cross the shard_map boundary with a
pytree spec (data + head_dim-less scale sharded congruently), which
is what dissolved the int8 x pp exclusivity rule.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from production_stack_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from production_stack_tpu.engine.config import ModelConfig
from production_stack_tpu.models.llama import (
    _layer_param_names,
    dispatch_attention,
    rms_norm,
)
from production_stack_tpu.models.gpt2 import (
    GPT2_LAYER_NAMES,
    layer_norm,
)
from production_stack_tpu.ops.attention import write_to_pages
from production_stack_tpu.ops.rope import apply_rope
from production_stack_tpu.parallel.mesh import (
    _on_mesh,
    cache_spec as mesh_cache_spec,
    param_specs,
)

Params = Dict[str, jnp.ndarray]


def _psum_tp(x, tp: int):
    return jax.lax.psum(x, "tp") if tp > 1 else x


def _lora_mm(x, w, ll, target, lora_ids, lora_scale):
    """Projection with optional LoRA delta, shared by the pp and sp
    shard_map bodies. Under tp the adapter stacks arrive sharded like
    their base projections (engine/lora.py lora_stack_specs):
    column-parallel targets add a local out/tp-wide delta to the local
    base; row-parallel targets contract a LOCAL input shard against
    the A shard, so base and delta are both partials the caller's
    psum closes together. ``w`` may be an int8 (weight, scale) pair:
    lora_matmul owns the dense/dequant dispatch and returns the plain
    base matmul when ``ll`` is None."""
    if ll is None and not isinstance(w, tuple):
        return x @ w  # skip the helper import on the hot plain path
    from production_stack_tpu.engine.lora import lora_matmul
    return lora_matmul(x, w, ll, target, lora_ids, lora_scale)


def _stage_layer(lp, i):
    """Slice layer ``i`` off each stage-local stack; int8 params are
    (weight, scale) pairs whose members slice together."""
    return {name: ((s[0][i], s[1][i]) if isinstance(s, tuple)
                   else s[i])
            for name, s in lp.items()}


def _local_layers_llama(x, lp, k_local, v_local, page_table, positions,
                        kv_lens, valid, config: ModelConfig, tp: int,
                        lora=None, lora_ids=None, lora_scale=None):
    """One stage's layer scan — the paged layer math of
    models/llama.py:forward (layer_step) with tp-local head counts."""
    nh = config.num_attention_heads // tp
    nkv = config.num_key_value_heads // tp
    d = config.head_dim
    b, t = positions.shape

    # Static loop over the stage's local layers, in-place cache
    # scatters at a static index (see models.llama.forward).
    for i in range(k_local.shape[0]):
        lp_i = _stage_layer(lp, i)
        ll = (None if lora is None
              else jax.tree.map(lambda s: s[i], lora))
        a_in = rms_norm(x, lp_i["attn_norm"], config.rms_norm_eps)
        q = _lora_mm(a_in, lp_i["wq"], ll, "wq", lora_ids, lora_scale)
        k = _lora_mm(a_in, lp_i["wk"], ll, "wk", lora_ids, lora_scale)
        v = _lora_mm(a_in, lp_i["wv"], ll, "wv", lora_ids, lora_scale)
        if config.attention_bias:
            q, k, v = q + lp_i["bq"], k + lp_i["bk"], v + lp_i["bv"]
        q = apply_rope(q.reshape(b, t, nh, d), positions,
                       config.rope_theta)
        k = apply_rope(k.reshape(b, t, nkv, d), positions,
                       config.rope_theta)
        v = v.reshape(b, t, nkv, d)
        k_local = write_to_pages(k_local, k, page_table, positions,
                                 valid, layer=i)
        v_local = write_to_pages(v_local, v, page_table, positions,
                                 valid, layer=i)
        attn, k_local, v_local = dispatch_attention(
            config, q, k_local, v_local, page_table, positions,
            kv_lens, layer=i,
        )
        x = x + _psum_tp(
            _lora_mm(attn.reshape(b, t, nh * d), lp_i["wo"], ll, "wo",
                     lora_ids, lora_scale), tp)
        m_in = rms_norm(x, lp_i["mlp_norm"], config.rms_norm_eps)
        x = x + _psum_tp(
            _lora_mm(
                jax.nn.silu(_lora_mm(m_in, lp_i["w_gate"], ll,
                                     "w_gate", lora_ids, lora_scale))
                * _lora_mm(m_in, lp_i["w_up"], ll, "w_up", lora_ids,
                           lora_scale),
                lp_i["w_down"], ll, "w_down", lora_ids, lora_scale),
            tp)
    return x, k_local, v_local


def _local_layers_gpt2(x, lp, k_local, v_local, page_table, positions,
                       kv_lens, valid, config: ModelConfig, tp: int,
                       lora=None, lora_ids=None, lora_scale=None):
    """GPT-2 stage body: pre-LN, learned positions are added before
    the first stage (embed path), gelu MLP, per-projection biases.
    Column biases (bq/bk/bv/fc1_b) arrive tp-sharded with their
    projections; row outputs psum over tp before the replicated
    bo/fc2_b is added once."""
    nh = config.num_attention_heads // tp
    d = config.head_dim
    b, t = positions.shape

    # Static loop over the stage's local layers, in-place cache
    # scatters at a static index (see models.llama.forward).
    for i in range(k_local.shape[0]):
        lp_i = _stage_layer(lp, i)
        ll = (None if lora is None
              else jax.tree.map(lambda s: s[i], lora))
        a_in = layer_norm(x, lp_i["attn_norm_w"], lp_i["attn_norm_b"])
        q = (_lora_mm(a_in, lp_i["wq"], ll, "wq", lora_ids, lora_scale)
             + lp_i["bq"]).reshape(b, t, nh, d)
        k = (_lora_mm(a_in, lp_i["wk"], ll, "wk", lora_ids, lora_scale)
             + lp_i["bk"]).reshape(b, t, nh, d)
        v = (_lora_mm(a_in, lp_i["wv"], ll, "wv", lora_ids, lora_scale)
             + lp_i["bv"]).reshape(b, t, nh, d)
        k_local = write_to_pages(k_local, k, page_table, positions,
                                 valid, layer=i)
        v_local = write_to_pages(v_local, v, page_table, positions,
                                 valid, layer=i)
        attn, k_local, v_local = dispatch_attention(
            config, q, k_local, v_local, page_table, positions,
            kv_lens, layer=i,
        )
        x = x + (_psum_tp(
            _lora_mm(attn.reshape(b, t, nh * d), lp_i["wo"], ll, "wo",
                     lora_ids, lora_scale), tp) + lp_i["bo"])
        m_in = layer_norm(x, lp_i["mlp_norm_w"], lp_i["mlp_norm_b"])
        hidden = jax.nn.gelu(
            _lora_mm(m_in, lp_i["fc1"], ll, "fc1", lora_ids,
                     lora_scale) + lp_i["fc1_b"],
            approximate=True)
        x = x + (_psum_tp(_lora_mm(hidden, lp_i["fc2"], ll, "fc2",
                                   lora_ids, lora_scale), tp)
                 + lp_i["fc2_b"])
    return x, k_local, v_local


def _embed(shared_p, config, tokens, positions, dtype):
    x = shared_p["embed"][tokens].astype(dtype)
    if config.architecture == "gpt2":
        x = x + shared_p["pos_embed"][positions].astype(dtype)
    return x


def _head(shared_p, config, hidden, tp: int):
    if config.architecture == "gpt2":
        x = layer_norm(hidden, shared_p["final_norm_w"],
                       shared_p["final_norm_b"])
        return (x @ shared_p["embed"].T).astype(jnp.float32)
    x = rms_norm(hidden, shared_p["final_norm"], config.rms_norm_eps)
    head = shared_p.get("lm_head")
    if head is None:
        return (x @ shared_p["embed"].T).astype(jnp.float32)
    # lm_head is column-sharded over tp (mesh.py _LLAMA_SPECS):
    # assemble the full vocab axis from the local shards.
    logits = (x @ head).astype(jnp.float32)
    if tp > 1:
        logits = jax.lax.all_gather(
            logits, "tp", axis=logits.ndim - 1, tiled=True)
    return logits


_LOCAL_LAYER_BODIES = {
    "llama": _local_layers_llama,
    "mistral": _local_layers_llama,
    "qwen2": _local_layers_llama,
    "gpt2": _local_layers_gpt2,
}

PP_FAMILIES = tuple(_LOCAL_LAYER_BODIES)


def pp_paged_forward(params: Params, config: ModelConfig,
                     tokens: jnp.ndarray, positions: jnp.ndarray,
                     page_table: jnp.ndarray, kv_lens: jnp.ndarray,
                     valid: jnp.ndarray, k_cache: jnp.ndarray,
                     v_cache: jnp.ndarray, lora=None, lora_ids=None,
                     *, mesh: Mesh,
                     ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Engine forward contract (models/llama.py:forward signature) with
    layers pipelined over the mesh's ``pp`` axis (and projections
    sharded over ``tp`` within each stage).

    k_cache/v_cache carry their GLOBAL shape [L, kv, pages, d, ps] but
    are sharded P('pp', 'tp') on (L, kv); inside the shard_map body
    each stage sees its local [L/S, kv/tp, ...] slice.
    """
    S = mesh.shape["pp"]
    tp = mesh.shape["tp"] if "tp" in mesh.axis_names else 1
    b, t = tokens.shape

    # Pad the batch to a multiple of S so M == S always (every stage
    # busy outside fill/drain); padded rows are valid=False.
    pad = (-b) % S
    if pad:
        tokens = jnp.pad(tokens, ((0, pad), (0, 0)))
        positions = jnp.pad(positions, ((0, pad), (0, 0)))
        page_table = jnp.pad(page_table, ((0, pad), (0, 0)))
        kv_lens = jnp.pad(kv_lens, ((0, pad),))
        valid = jnp.pad(valid, ((0, pad), (0, 0)))
    bp = b + pad
    M = min(S, bp)
    mb = bp // M

    local_layers = _LOCAL_LAYER_BODIES[config.architecture]
    layer_names = (list(GPT2_LAYER_NAMES)
                   if config.architecture == "gpt2"
                   else _layer_param_names(config))
    layer_params = {k: params[k] for k in layer_names}
    shared = {k: v for k, v in params.items() if k not in layer_names}
    max_pages = page_table.shape[1]
    # LoRA adapter stacks shard their leading L axis over pp with the
    # other layer params; scaling/ids replicate. Padded batch rows run
    # as base model (slot 0 is the all-zeros adapter).
    lora_ab = (None if lora is None
               else {"a": lora["a"], "b": lora["b"]})
    if lora_ids is not None and pad:
        lora_ids = jnp.pad(lora_ids, ((0, pad),))
    lora_scale = (None if lora is None
                  else lora["scaling"][lora_ids])

    def body(lp, shared_p, kc, vc, tokens, positions, page_table,
             kv_lens, valid, lora_ab, lora_ids, lora_scale):
        stage = jax.lax.axis_index("pp")
        mtok = tokens.reshape(M, mb, t)
        mpos = positions.reshape(M, mb, t)
        mpt = page_table.reshape(M, mb, max_pages)
        mkv = kv_lens.reshape(M, mb)
        mvalid = valid.reshape(M, mb, t)
        mlid = (None if lora_ids is None
                else lora_ids.reshape(M, mb))
        mlsc = (None if lora_scale is None
                else lora_scale.reshape(M, mb))
        h = config.hidden_size
        dtype = shared_p["embed"].dtype
        ticks = M + S - 1
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, i):
            x_recv, kc, vc, collected = carry
            # Stage s processes microbatch i - s at tick i.
            m_s = jnp.clip(i - stage, 0, M - 1)
            active = (i >= stage) & (i - stage < M)
            emb = _embed(shared_p, config, mtok[m_s], mpos[m_s], dtype)
            x_in = jnp.where(stage == 0, emb, x_recv)
            # Bubble ticks must not touch the cache: a False valid
            # redirects the write to the trash page (ops/attention.py
            # write_to_pages).
            v_mask = mvalid[m_s] & active
            x_new, kc, vc = local_layers(
                x_in, lp, kc, vc, mpt[m_s], mpos[m_s], mkv[m_s],
                v_mask, config, tp,
                lora=lora_ab,
                lora_ids=None if mlid is None else mlid[m_s],
                lora_scale=None if mlsc is None else mlsc[m_s],
            )
            # Last stage banks microbatch i - (S - 1) once it's real.
            take = (stage == S - 1) & (i >= S - 1)
            banked = collected.at[jnp.clip(i - (S - 1), 0, M - 1)].set(
                x_new)
            collected = jnp.where(take, banked, collected)
            x_send = jax.lax.ppermute(x_new, "pp", perm)
            return (x_send, kc, vc, collected), None

        init = (
            jnp.zeros((mb, t, h), dtype),
            kc, vc,
            jnp.zeros((M, mb, t, h), dtype),
        )
        (_, kc, vc, collected), _ = jax.lax.scan(
            tick, init, jnp.arange(ticks)
        )
        # Return the final HIDDEN states to every stage (one masked
        # psum of [B, T, H] — serving shapes keep this small) and
        # compute the logits locally.
        collected = jnp.where(stage == S - 1, collected, 0.0)
        hidden = jax.lax.psum(collected, "pp").reshape(bp, t, h)
        return _head(shared_p, config, hidden, tp), kc, vc

    # Layer params keep their TP column/row specs with the leading L
    # axis mapped to 'pp' — exactly how shard_params placed them. A
    # mesh without a 'tp' axis (pp-only callers) must still work:
    # drop axis names the mesh doesn't have.
    def on_mesh(spec: P) -> P:
        return _on_mesh(spec, mesh)

    specs = param_specs(config)

    def lp_spec(k):
        spec = on_mesh(P("pp", *specs[k][1:]))
        if isinstance(layer_params[k], tuple):
            # int8 (weight [L, in, out], scale [L, out]): the scale
            # follows the weight's layer + output-channel axes
            # (mirrors parallel/mesh.py shard_params).
            return (spec, P(spec[0], spec[2]))
        return spec

    lp_specs = {k: lp_spec(k) for k in layer_params}
    shared_specs = {k: on_mesh(specs.get(k, P())) for k in shared}
    cache_spec = on_mesh(mesh_cache_spec(mesh))
    # QuantKV caches (int8 pages + per-slot f32 scales) cross the
    # shard_map boundary as a pytree spec: the 4-D scale leaf lacks
    # the head_dim axis, so its spec drops that entry — congruent
    # data+scale sharding, mirroring parallel/mesh.py shard_cache.
    from production_stack_tpu.ops.quant_kv import QuantKV
    if isinstance(k_cache, QuantKV):
        cache_spec = QuantKV(cache_spec,
                             P(*cache_spec[:3], cache_spec[4]))
    repl = P()
    # Adapter stacks: leading L over pp; under tp each target shards
    # like its base projection (the shared rule —
    # engine/lora.py lora_stack_specs). ids/scaling replicate.
    # _on_mesh drops 'tp' on pp-only meshes, degrading every spec to
    # the old P('pp').
    if lora_ab is None:
        lora_ab_spec = P("pp")
    else:
        from production_stack_tpu.engine.lora import lora_stack_specs
        lora_ab_spec = lora_stack_specs(lora_ab, "pp", on_mesh)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(lp_specs, shared_specs, cache_spec, cache_spec,
                  repl, repl, repl, repl, repl,
                  lora_ab_spec, repl, repl),
        out_specs=(repl, cache_spec, cache_spec),
        check_vma=False,
    )
    logits, kc, vc = fn(layer_params, shared, k_cache, v_cache, tokens,
                        positions, page_table, kv_lens, valid,
                        lora_ab, lora_ids, lora_scale)
    return logits[:b], kc, vc
