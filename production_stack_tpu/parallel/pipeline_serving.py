"""Pipeline-parallel SERVING forward: paged-KV layer stages over ``pp``.

The reference stack exposes no pipeline parallelism (SURVEY.md §2.6 —
vLLM TP only via --tensor-parallel-size pass-through); this is the
TPU-native extension that serves models deeper than one chip/slice's
HBM. Unlike ``parallel/pipeline.py`` (a dense training-style forward),
this implements the ENGINE's forward contract — paged KV cache writes,
chunked prefill, decode — so ``--pipeline-parallel-size N`` is a real
serving flag (engine/server.py).

Design (idiomatic JAX, static shapes):
- Layer-stacked params and the KV caches shard their leading L axis
  over the ``pp`` mesh axis; each stage owns L/S layers and those
  layers' KV pages. Embedding/head replicate.
- One ``shard_map`` body runs a static tick loop (M microbatches over
  the batch rows, S stages, M+S-1 ticks). At tick i, stage s runs its
  local layer scan on microbatch i-s; activations hop stage-to-stage
  with ``ppermute`` over ICI/DCN.
- Bubble ticks compute on don't-care data; their KV writes are masked
  via the ``valid`` mask, which ``ops.attention.write_to_pages``
  redirects to the trash page (page 0) — no cache corruption, no
  dynamic shapes.
- The final hidden states (NOT logits: H << vocab, 16x less traffic)
  are returned to every stage with one masked psum; each stage then
  computes the replicated logits locally. This replaces the training
  pipeline's full-activation psum the round-1 review flagged.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from production_stack_tpu.engine.config import ModelConfig
from production_stack_tpu.models.llama import (
    _layer_param_names,
    dispatch_attention,
    rms_norm,
)
from production_stack_tpu.ops.attention import write_to_pages
from production_stack_tpu.ops.rope import apply_rope

Params = Dict[str, jnp.ndarray]


def _num_microbatches(batch: int, stages: int) -> int:
    """Largest microbatch count <= stages that divides the batch (1 =
    sequential fill/drain; == stages hides the bubble best)."""
    for m in range(min(batch, stages), 0, -1):
        if batch % m == 0:
            return m
    return 1


def _local_layers(x, lp, k_local, v_local, page_table, positions,
                  kv_lens, valid, config: ModelConfig):
    """One stage's layer scan — the paged layer math of
    models/llama.py:forward (layer_step), minus LoRA (pp+LoRA is
    rejected at engine build)."""
    nh, nkv, d = (config.num_attention_heads,
                  config.num_key_value_heads, config.head_dim)
    b, t = positions.shape

    def layer_step(x, scanned):
        lp_i, k_layer, v_layer = scanned
        a_in = rms_norm(x, lp_i["attn_norm"], config.rms_norm_eps)
        q = a_in @ lp_i["wq"]
        k = a_in @ lp_i["wk"]
        v = a_in @ lp_i["wv"]
        if config.attention_bias:
            q, k, v = q + lp_i["bq"], k + lp_i["bk"], v + lp_i["bv"]
        q = apply_rope(q.reshape(b, t, nh, d), positions,
                       config.rope_theta)
        k = apply_rope(k.reshape(b, t, nkv, d), positions,
                       config.rope_theta)
        v = v.reshape(b, t, nkv, d)
        k_layer = write_to_pages(k_layer, k, page_table, positions,
                                 valid)
        v_layer = write_to_pages(v_layer, v, page_table, positions,
                                 valid)
        attn = dispatch_attention(
            config, q, k_layer, v_layer, page_table, positions, kv_lens
        )
        x = x + attn.reshape(b, t, nh * d) @ lp_i["wo"]
        m_in = rms_norm(x, lp_i["mlp_norm"], config.rms_norm_eps)
        x = x + (jax.nn.silu(m_in @ lp_i["w_gate"])
                 * (m_in @ lp_i["w_up"])) @ lp_i["w_down"]
        return x, (k_layer, v_layer)

    x, (new_k, new_v) = jax.lax.scan(
        layer_step, x, (lp, k_local, v_local)
    )
    return x, new_k, new_v


def pp_paged_forward(params: Params, config: ModelConfig,
                     tokens: jnp.ndarray, positions: jnp.ndarray,
                     page_table: jnp.ndarray, kv_lens: jnp.ndarray,
                     valid: jnp.ndarray, k_cache: jnp.ndarray,
                     v_cache: jnp.ndarray, lora=None, lora_ids=None,
                     *, mesh: Mesh,
                     ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Engine forward contract (models/llama.py:forward signature) with
    layers pipelined over the mesh's ``pp`` axis.

    k_cache/v_cache carry their GLOBAL shape [L, kv, pages, ps, d] but
    are sharded P('pp') on L; inside the shard_map body each stage sees
    its local [L/S, ...] slice.
    """
    if lora is not None:
        raise NotImplementedError("LoRA with pipeline parallelism")
    S = mesh.shape["pp"]
    b, t = tokens.shape
    M = _num_microbatches(b, S)
    mb = b // M

    layer_names = _layer_param_names(config)
    layer_params = {k: params[k] for k in layer_names}
    shared = {k: v for k, v in params.items() if k not in layer_names}
    max_pages = page_table.shape[1]

    def body(lp, shared_p, kc, vc, tokens, positions, page_table,
             kv_lens, valid):
        stage = jax.lax.axis_index("pp")
        mtok = tokens.reshape(M, mb, t)
        mpos = positions.reshape(M, mb, t)
        mpt = page_table.reshape(M, mb, max_pages)
        mkv = kv_lens.reshape(M, mb)
        mvalid = valid.reshape(M, mb, t)
        h = config.hidden_size
        dtype = shared_p["embed"].dtype
        ticks = M + S - 1
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, i):
            x_recv, kc, vc, collected = carry
            # Stage s processes microbatch i - s at tick i.
            m_s = jnp.clip(i - stage, 0, M - 1)
            active = (i >= stage) & (i - stage < M)
            emb = shared_p["embed"][mtok[m_s]].astype(dtype)
            x_in = jnp.where(stage == 0, emb, x_recv)
            # Bubble ticks must not touch the cache: a False valid
            # redirects the write to the trash page (ops/attention.py
            # write_to_pages).
            v_mask = mvalid[m_s] & active
            x_new, kc, vc = _local_layers(
                x_in, lp, kc, vc, mpt[m_s], mpos[m_s], mkv[m_s],
                v_mask, config,
            )
            # Last stage banks microbatch i - (S - 1) once it's real.
            take = (stage == S - 1) & (i >= S - 1)
            banked = collected.at[jnp.clip(i - (S - 1), 0, M - 1)].set(
                x_new)
            collected = jnp.where(take, banked, collected)
            x_send = jax.lax.ppermute(x_new, "pp", perm)
            return (x_send, kc, vc, collected), None

        init = (
            jnp.zeros((mb, t, h), dtype),
            kc, vc,
            jnp.zeros((M, mb, t, h), dtype),
        )
        (_, kc, vc, collected), _ = jax.lax.scan(
            tick, init, jnp.arange(ticks)
        )
        # Return the final HIDDEN states to every stage (one masked
        # psum of [B, T, H] — serving shapes keep this small) and
        # compute the replicated logits locally.
        collected = jnp.where(stage == S - 1, collected, 0.0)
        hidden = jax.lax.psum(collected, "pp").reshape(b, t, h)
        x = rms_norm(hidden, shared_p["final_norm"],
                     config.rms_norm_eps)
        head = shared_p.get("lm_head")
        if head is None:
            head = shared_p["embed"].T
        logits = (x @ head).astype(jnp.float32)
        return logits, kc, vc

    pp_only = P("pp")
    repl = P()
    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=({k: pp_only for k in layer_params},
                  {k: repl for k in shared},
                  pp_only, pp_only, repl, repl, repl, repl, repl),
        out_specs=(repl, pp_only, pp_only),
        check_vma=False,
    )
    return fn(layer_params, shared, k_cache, v_cache, tokens,
              positions, page_table, kv_lens, valid)
