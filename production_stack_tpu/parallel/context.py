"""Context parallelism: full-model forward with the sequence sharded
over the ``sp`` mesh axis.

The reference has no sequence/context parallelism (verified in SURVEY.md
§2.6 — nothing in repo); its long-context story is flag pass-through to
vLLM. Here a long prompt is a first-class distributed object: activations
are sharded [B, T/n] per device, attention runs as ring attention
(ops/ring_attention.py, K/V hops over ICI via ppermute), and everything
else (norms, projections, MLP) is purely local so XLA keeps the MXU busy
between hops. Combined with the ``dp`` axis for batch sharding this is
the dp x sp layout of the scaling-book recipe; ``tp`` composes by
sharding the head dimension of the same shard_map block.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from production_stack_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from production_stack_tpu.engine.config import ModelConfig
from production_stack_tpu.models.llama import rms_norm as _rms_norm
from production_stack_tpu.ops.ring_attention import ring_attention
from production_stack_tpu.ops.rope import apply_rope

Params = Dict[str, jnp.ndarray]


def _local_forward(params: Params, tokens: jnp.ndarray,
                   config: ModelConfig, sp_axis: str) -> jnp.ndarray:
    """Per-device body: local activations, ring attention for mixing.

    tokens: [B_local, T_local] — this device's slice of the batch and
    sequence. Positions are global: sp shard i covers
    [i*T_local, (i+1)*T_local).
    """
    nh, nkv, d = (config.num_attention_heads, config.num_key_value_heads,
                  config.head_dim)
    b, t = tokens.shape
    idx = jax.lax.axis_index(sp_axis)
    positions = idx * t + jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))

    x = params["embed"][tokens]

    layer_params = {
        k: params[k] for k in (
            "attn_norm", "wq", "wk", "wv", "wo",
            "mlp_norm", "w_gate", "w_up", "w_down",
        )
    }

    def layer_step(x, lp):
        a_in = _rms_norm(x, lp["attn_norm"], config.rms_norm_eps)
        q = apply_rope((a_in @ lp["wq"]).reshape(b, t, nh, d),
                       positions, config.rope_theta)
        k = apply_rope((a_in @ lp["wk"]).reshape(b, t, nkv, d),
                       positions, config.rope_theta)
        v = (a_in @ lp["wv"]).reshape(b, t, nkv, d)
        attn = ring_attention(q, k, v, axis_name=sp_axis, causal=True)
        x = x + attn.reshape(b, t, nh * d) @ lp["wo"]
        m_in = _rms_norm(x, lp["mlp_norm"], config.rms_norm_eps)
        x = x + (jax.nn.silu(m_in @ lp["w_gate"])
                 * (m_in @ lp["w_up"])) @ lp["w_down"]
        return x, None

    x, _ = jax.lax.scan(layer_step, x, layer_params)
    x = _rms_norm(x, params["final_norm"], config.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    return (x @ head).astype(jnp.float32)


def context_parallel_forward(params: Params, config: ModelConfig,
                             tokens: jnp.ndarray, mesh: Mesh,
                             sp_axis: str = "sp",
                             dp_axis: Optional[str] = "dp",
                             ) -> jnp.ndarray:
    """Dense causal forward (same numerics as ``llama.forward_train``)
    with sequence sharded over ``sp`` and batch over ``dp``.

    tokens: global [B, T]; T must divide by the sp-axis size, B by the
    dp-axis size (if present in the mesh). Returns global logits
    [B, T, vocab] (sharded the same way).
    """
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    batch_axis = dp_axis if (dp_axis and dp_axis in axes
                             and axes[dp_axis] > 1) else None
    tok_spec = P(batch_axis, sp_axis)
    out_spec = P(batch_axis, sp_axis, None)

    fn = shard_map(
        partial(_local_forward, config=config, sp_axis=sp_axis),
        mesh=mesh,
        in_specs=(P(), tok_spec),
        out_specs=out_spec,
        check_vma=False,
    )
    return fn(params, tokens)
