"""Pipeline parallelism: layer stages over a ``pp`` mesh axis.

The reference exposes no pipeline parallelism (SURVEY.md §2.6 — vLLM
TP only); this is the TPU-native extension for models deeper than one
slice's HBM: the layer-stacked parameters shard their leading L axis
across pp stages, and a GPipe-style microbatch schedule streams
activations stage-to-stage with ``ppermute`` hops over ICI/DCN.

Idiomatic-JAX shape: one ``shard_map`` block; inside it each stage
scans a static tick loop of length M + S - 1 (M microbatches, S
stages). At tick t, stage s processes microbatch t - s: stage 0 embeds
a fresh microbatch, inner stages run their local layer block on the
activation received last tick, the last stage collects final hidden
states. All stages execute every tick (bubble ticks compute on zeros —
the XLA-friendly trade for a static schedule).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from production_stack_tpu.utils.compat import shard_map
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from production_stack_tpu.engine.config import ModelConfig
from production_stack_tpu.models.llama import rms_norm
from production_stack_tpu.ops.rope import apply_rope

Params = Dict[str, jnp.ndarray]


def _layer_block(x, lp, config: ModelConfig, positions):
    """Apply one stage's stack of dense causal layers (same numerics
    as models.llama.encode's layer_step)."""
    nh, nkv, d = (config.num_attention_heads,
                  config.num_key_value_heads, config.head_dim)
    b, t, _ = x.shape
    causal = jnp.tril(jnp.ones((t, t), bool))

    def step(x, lp_i):
        a_in = rms_norm(x, lp_i["attn_norm"], config.rms_norm_eps)
        q = a_in @ lp_i["wq"]
        k = a_in @ lp_i["wk"]
        v = a_in @ lp_i["wv"]
        if config.attention_bias:
            q, k, v = (q + lp_i["bq"], k + lp_i["bk"], v + lp_i["bv"])
        q = apply_rope(q.reshape(b, t, nh, d), positions,
                       config.rope_theta)
        k = apply_rope(k.reshape(b, t, nkv, d), positions,
                       config.rope_theta)
        v = v.reshape(b, t, nkv, d)
        group = nh // nkv
        qg = q.reshape(b, t, nkv, group, d)
        scores = jnp.einsum(
            "btkgd,bskd->bkgts", qg.astype(jnp.float32),
            k.astype(jnp.float32),
        ) / jnp.sqrt(jnp.asarray(d, jnp.float32))
        scores = jnp.where(causal[None, None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum(
            "bkgts,bskd->btkgd", probs, v.astype(jnp.float32)
        ).reshape(b, t, nh * d).astype(x.dtype)
        x = x + attn @ lp_i["wo"]
        m_in = rms_norm(x, lp_i["mlp_norm"], config.rms_norm_eps)
        x = x + (jax.nn.silu(m_in @ lp_i["w_gate"])
                 * (m_in @ lp_i["w_up"])) @ lp_i["w_down"]
        return x, None

    x, _ = jax.lax.scan(step, x, lp)
    return x


def _layer_param_names(config: ModelConfig):
    names = ["attn_norm", "wq", "wk", "wv", "wo",
             "mlp_norm", "w_gate", "w_up", "w_down"]
    if config.attention_bias:
        names += ["bq", "bk", "bv"]
    return names


def pipeline_forward(params: Params, config: ModelConfig,
                     tokens: jnp.ndarray, mesh: Mesh,
                     pp_axis: str = "pp",
                     num_microbatches: Optional[int] = None
                     ) -> jnp.ndarray:
    """Dense causal forward with layers pipelined over ``pp_axis``.

    Args:
      params: llama-family stacked params (models/llama.py layout);
        layer count must divide by the pp-axis size.
      tokens: [B, T]; B must divide by num_microbatches.
      num_microbatches: defaults to the pp-axis size.

    Returns logits [B, T, vocab] (replicated).
    """
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    S = axes[pp_axis]
    M = num_microbatches or S
    b, t = tokens.shape
    if b % M:
        raise ValueError(f"batch {b} must divide by microbatches {M}")
    L = config.num_hidden_layers
    if L % S:
        raise ValueError(f"layers {L} must divide by pp size {S}")
    mb = b // M
    positions = jnp.broadcast_to(jnp.arange(t)[None, :], (mb, t))

    layer_names = _layer_param_names(config)
    layer_params = {k: params[k] for k in layer_names}
    shared = {k: v for k, v in params.items() if k not in layer_names}

    layer_specs = {k: P(pp_axis) for k in layer_params}
    none_spec = P(*([None] * 0))

    if M % S:
        raise ValueError(
            f"microbatches {M} must divide by pp size {S} (outputs "
            "shard M over the stages)")
    mps = M // S  # microbatches homed per stage

    def stage_fn(layer_local, shared_p, tokens_all):
        stage = jax.lax.axis_index(pp_axis)
        ticks = M + S - 1
        # Microbatch views: [M, mb, T]
        mbs = tokens_all.reshape(M, mb, t)
        h = config.hidden_size
        dtype = shared_p["embed"].dtype
        shift = [(i, (i + 1) % S) for i in range(S)]

        # The tick loop is UNROLLED (M + S - 1 is small and static) so
        # every collective uses a static permutation. Finished
        # microbatch m is delivered straight from the last stage to its
        # home stage m // mps — one [mb,T,H] hop each — and outputs
        # stay SHARDED over pp (out_specs P(pp_axis)); no full-tensor
        # psum broadcast (round-1 review finding).
        recv = jnp.zeros((mb, t, h), dtype)
        collected = jnp.zeros((mps, mb, t, h), dtype)
        for t_idx in range(ticks):
            # Stage 0 feeds microbatch t_idx (clamped; bubble ticks
            # re-embed a stale microbatch and are ignored downstream).
            m_idx = min(t_idx, M - 1)
            embedded = shared_p["embed"][mbs[m_idx]]
            x = jnp.where(stage == 0, embedded.astype(dtype), recv)
            x = _layer_block(x, layer_local, config, positions)
            recv = jax.lax.ppermute(x, pp_axis, shift)
            m_done = t_idx - (S - 1)
            if m_done >= 0:
                home, slot = m_done // mps, m_done % mps
                if home == S - 1:
                    delivered = x  # already on the last stage
                else:
                    delivered = jax.lax.ppermute(
                        x, pp_axis, [(S - 1, home)])
                collected = jnp.where(
                    stage == home,
                    collected.at[slot].set(delivered),
                    collected,
                )
        x = rms_norm(collected.reshape(mps * mb, t, h),
                     shared_p["final_norm"], config.rms_norm_eps)
        head = shared_p.get("lm_head")
        if head is None:
            head = shared_p["embed"].T
        return (x @ head).astype(jnp.float32)

    fn = shard_map(
        stage_fn, mesh=mesh,
        in_specs=(layer_specs, {k: none_spec for k in shared},
                  none_spec),
        out_specs=P(pp_axis),
        check_vma=False,
    )
    # Device s returns its mps home microbatches; the pp-sharded global
    # result is already in microbatch order (homes are contiguous
    # blocks), so a reshape recovers [B, T, vocab].
    return fn(layer_params, shared, tokens).reshape(b, t, -1)


def shard_params_pipeline(params: Params, config: ModelConfig,
                          mesh: Mesh, pp_axis: str = "pp") -> Params:
    """Place layer-stacked params with their L axis sharded across the
    pp stages (everything else replicated)."""
    from jax.sharding import NamedSharding
    layer_names = set(_layer_param_names(config))
    out = {}
    for k, v in params.items():
        spec = (P(pp_axis) if k in layer_names else P())
        out[k] = jax.device_put(v, NamedSharding(mesh, spec))
    return out
