"""Pipeline parallelism: layer stages over a ``pp`` mesh axis.

The reference exposes no pipeline parallelism (SURVEY.md §2.6 — vLLM
TP only); this is the TPU-native extension for models deeper than one
slice's HBM: the layer-stacked parameters shard their leading L axis
across pp stages, and a GPipe-style microbatch schedule streams
activations stage-to-stage with ``ppermute`` hops over ICI/DCN.

Idiomatic-JAX shape: one ``shard_map`` block; inside it each stage
scans a static tick loop of length M + S - 1 (M microbatches, S
stages). At tick t, stage s processes microbatch t - s: stage 0 embeds
a fresh microbatch, inner stages run their local layer block on the
activation received last tick, the last stage collects final hidden
states. All stages execute every tick (bubble ticks compute on zeros —
the XLA-friendly trade for a static schedule).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from production_stack_tpu.engine.config import ModelConfig
from production_stack_tpu.models.llama import rms_norm
from production_stack_tpu.ops.rope import apply_rope

Params = Dict[str, jnp.ndarray]


def _layer_block(x, lp, config: ModelConfig, positions):
    """Apply one stage's stack of dense causal layers (same numerics
    as models.llama.encode's layer_step)."""
    nh, nkv, d = (config.num_attention_heads,
                  config.num_key_value_heads, config.head_dim)
    b, t, _ = x.shape
    causal = jnp.tril(jnp.ones((t, t), bool))

    def step(x, lp_i):
        a_in = rms_norm(x, lp_i["attn_norm"], config.rms_norm_eps)
        q = a_in @ lp_i["wq"]
        k = a_in @ lp_i["wk"]
        v = a_in @ lp_i["wv"]
        if config.attention_bias:
            q, k, v = (q + lp_i["bq"], k + lp_i["bk"], v + lp_i["bv"])
        q = apply_rope(q.reshape(b, t, nh, d), positions,
                       config.rope_theta)
        k = apply_rope(k.reshape(b, t, nkv, d), positions,
                       config.rope_theta)
        v = v.reshape(b, t, nkv, d)
        group = nh // nkv
        qg = q.reshape(b, t, nkv, group, d)
        scores = jnp.einsum(
            "btkgd,bskd->bkgts", qg.astype(jnp.float32),
            k.astype(jnp.float32),
        ) / jnp.sqrt(jnp.asarray(d, jnp.float32))
        scores = jnp.where(causal[None, None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum(
            "bkgts,bskd->btkgd", probs, v.astype(jnp.float32)
        ).reshape(b, t, nh * d).astype(x.dtype)
        x = x + attn @ lp_i["wo"]
        m_in = rms_norm(x, lp_i["mlp_norm"], config.rms_norm_eps)
        x = x + (jax.nn.silu(m_in @ lp_i["w_gate"])
                 * (m_in @ lp_i["w_up"])) @ lp_i["w_down"]
        return x, None

    x, _ = jax.lax.scan(step, x, lp)
    return x


def _layer_param_names(config: ModelConfig):
    names = ["attn_norm", "wq", "wk", "wv", "wo",
             "mlp_norm", "w_gate", "w_up", "w_down"]
    if config.attention_bias:
        names += ["bq", "bk", "bv"]
    return names


def pipeline_forward(params: Params, config: ModelConfig,
                     tokens: jnp.ndarray, mesh: Mesh,
                     pp_axis: str = "pp",
                     num_microbatches: Optional[int] = None
                     ) -> jnp.ndarray:
    """Dense causal forward with layers pipelined over ``pp_axis``.

    Args:
      params: llama-family stacked params (models/llama.py layout);
        layer count must divide by the pp-axis size.
      tokens: [B, T]; B must divide by num_microbatches.
      num_microbatches: defaults to the pp-axis size.

    Returns logits [B, T, vocab] (replicated).
    """
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    S = axes[pp_axis]
    M = num_microbatches or S
    b, t = tokens.shape
    if b % M:
        raise ValueError(f"batch {b} must divide by microbatches {M}")
    L = config.num_hidden_layers
    if L % S:
        raise ValueError(f"layers {L} must divide by pp size {S}")
    mb = b // M
    positions = jnp.broadcast_to(jnp.arange(t)[None, :], (mb, t))

    layer_names = _layer_param_names(config)
    layer_params = {k: params[k] for k in layer_names}
    shared = {k: v for k, v in params.items() if k not in layer_names}

    layer_specs = {k: P(pp_axis) for k in layer_params}
    none_spec = P(*([None] * 0))

    def stage_fn(layer_local, shared_p, tokens_all):
        stage = jax.lax.axis_index(pp_axis)
        ticks = M + S - 1
        # Microbatch views: [M, mb, T]
        mbs = tokens_all.reshape(M, mb, t)
        h = config.hidden_size

        def tick(carry, t_idx):
            recv, collected = carry
            # Stage 0 feeds microbatch t_idx (clamped; bubble ticks
            # re-embed a stale microbatch and are ignored downstream).
            m_idx = jnp.clip(t_idx, 0, M - 1)
            embedded = shared_p["embed"][mbs[m_idx]]
            x = jnp.where(stage == 0, embedded.astype(recv.dtype),
                          recv)
            x = _layer_block(x, layer_local, config, positions)
            # Shift activations to the next stage; the last stage's
            # output wraps to stage 0 where it is ignored.
            perm = [(i, (i + 1) % S) for i in range(S)]
            sent = jax.lax.ppermute(x, pp_axis, perm)
            # Last stage collects microbatch t_idx - (S - 1).
            out_idx = jnp.clip(t_idx - (S - 1), 0, M - 1)
            take = (stage == S - 1) & (t_idx >= S - 1)
            collected = jnp.where(
                take,
                collected.at[out_idx].set(x),
                collected,
            )
            return (sent, collected), None

        init = (
            jnp.zeros((mb, t, h), shared_p["embed"].dtype),
            jnp.zeros((M, mb, t, h), shared_p["embed"].dtype),
        )
        (_, collected), _ = jax.lax.scan(
            tick, init, jnp.arange(ticks)
        )
        # Only the last stage holds real data; sum-broadcast it.
        collected = jnp.where(stage == S - 1, collected, 0.0)
        collected = jax.lax.psum(collected, pp_axis)
        x = rms_norm(collected.reshape(b, t, h), shared_p["final_norm"],
                     config.rms_norm_eps)
        head = shared_p.get("lm_head")
        if head is None:
            head = shared_p["embed"].T
        return (x @ head).astype(jnp.float32)

    fn = jax.shard_map(
        stage_fn, mesh=mesh,
        in_specs=(layer_specs, {k: none_spec for k in shared},
                  none_spec),
        out_specs=none_spec,
        check_vma=False,
    )
    return fn(layer_params, shared, tokens)


def shard_params_pipeline(params: Params, config: ModelConfig,
                          mesh: Mesh, pp_axis: str = "pp") -> Params:
    """Place layer-stacked params with their L axis sharded across the
    pp stages (everything else replicated)."""
    from jax.sharding import NamedSharding
    layer_names = set(_layer_param_names(config))
    out = {}
    for k, v in params.items():
        spec = (P(pp_axis) if k in layer_names else P())
        out[k] = jax.device_put(v, NamedSharding(mesh, spec))
    return out
