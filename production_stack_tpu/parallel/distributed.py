"""Multi-host serving: jax.distributed runtime + step-plan broadcast.

The reference's engines scale across hosts with vLLM's NCCL/Ray stack,
provisioned by the chart (/dev/shm, GPU resources —
deployment-vllm-multi.yaml:84-87,226-233). The TPU equivalent is JAX's
multi-controller model: every host of a slice runs this same program,
``jax.distributed.initialize`` wires the slice together, and jitted
steps over a global ``Mesh`` execute SPMD with XLA collectives riding
ICI (intra-slice) / DCN (inter-slice).

Serving needs one extra piece the SPMD model doesn't give us: the
scheduler (request queue, page allocator) lives only on host 0, but
every host must dispatch the SAME device program each step. The
``MultihostStepBridge`` closes that gap: host 0 authors a step payload
(numpy arrays) and broadcasts it; followers run a receive-execute
loop. All hosts then enter the same compiled step with identical
inputs, so the device programs line up without any per-step consensus
protocol.

The bridge speaks through a pluggable *endpoint* (docs/parallelism.md
§bridge-protocol): ``JaxBroadcastEndpoint`` rides
``multihost_utils.broadcast_one_to_all`` on a real multi-process
deployment, and ``FakeTransport`` provides in-process queue-backed
endpoints so tier-1 tests exercise the exact publish/receive/execute
sequence — including the template structural check and follower step
ordering — without spawning processes. Per-slice liveness
(``SliceLiveness``) rides the same plumbing: followers ack each
executed step (fake transport) or the collective's completion marks
everyone live (real transport — a dead host would hang the
broadcast, which the step watchdog surfaces), so a dead host names
ONE slice on /metrics instead of indicting the whole pool.
"""

from __future__ import annotations

import copy
import threading
import time
from typing import Dict, List, Optional

import numpy as np

import jax

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

KIND_SHUTDOWN = 0
KIND_PREFILL = 1
KIND_DECODE = 2
KIND_EMBED = 3  # /v1/embeddings|score|rerank batches (engine/embeddings.py)
KIND_SPEC = 4  # speculative verify step (docs/speculative.md)
KIND_UNIFIED = 5  # mixed ragged step (docs/unified_step.md)


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Join the jax.distributed runtime.

    On GKE TPU slices all arguments auto-detect from the TPU metadata;
    explicit values support bare-metal/CPU rigs (the reference's
    bare-metal flow analogue, run_production_stack/).
    """
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    logger.info("jax.distributed up: process %d/%d, %d local / %d "
                "global devices", jax.process_index(),
                jax.process_count(), jax.local_device_count(),
                jax.device_count())


def is_coordinator() -> bool:
    return jax.process_index() == 0


# ---- liveness ----------------------------------------------------------


class SliceLiveness:
    """Per-slice liveness ledger: a slice is live while at least one
    of its hosts has been seen within ``timeout_s``.

    Fed by follower acks (fake transport) or collective completion
    (real transport). The point of keying on SLICES rather than the
    pool: when a host dies, /metrics names the one slice to drain and
    replace — the rest of the fleet stays green.
    """

    def __init__(self, num_slices: int = 1, timeout_s: float = 10.0):
        self.num_slices = max(1, int(num_slices))
        self.timeout_s = timeout_s
        now = time.monotonic()
        self._last: Dict[int, float] = {
            i: now for i in range(self.num_slices)}
        self._lock = threading.Lock()

    def heartbeat(self, slice_id: int,
                  now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            if slice_id in self._last:
                self._last[slice_id] = max(self._last[slice_id], now)

    def mark_all(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            for i in self._last:
                self._last[i] = max(self._last[i], now)

    def snapshot(self, now: Optional[float] = None) -> Dict[int, bool]:
        """slice_id -> live?"""
        now = time.monotonic() if now is None else now
        with self._lock:
            return {i: (now - t) <= self.timeout_s
                    for i, t in sorted(self._last.items())}

    def dead_slices(self, now: Optional[float] = None) -> List[int]:
        return [i for i, live in self.snapshot(now).items()
                if not live]


# ---- transports --------------------------------------------------------


def _template_mismatch(template, value) -> Optional[str]:
    """Structural diff between a receive template and the payload that
    actually arrived: None when they agree, else a reason string. The
    real broadcast enforces this implicitly (shape-mismatched
    collectives corrupt or hang); the fake transport enforces it
    loudly so tier-1 catches template drift."""
    if isinstance(template, dict) or isinstance(value, dict):
        if not (isinstance(template, dict) and isinstance(value, dict)):
            return (f"kind mismatch: template {type(template).__name__}"
                    f" vs payload {type(value).__name__}")
        if set(template) != set(value):
            missing = sorted(set(template) - set(value))
            extra = sorted(set(value) - set(template))
            return f"key drift: missing={missing} extra={extra}"
        for k in template:
            why = _template_mismatch(template[k], value[k])
            if why is not None:
                return f"{k}: {why}"
        return None
    t, v = np.asarray(template), np.asarray(value)
    if t.shape != v.shape:
        return f"shape {t.shape} vs {v.shape}"
    if t.dtype != v.dtype:
        return f"dtype {t.dtype} vs {v.dtype}"
    return None


class JaxBroadcastEndpoint:
    """Real transport: host 0's value reaches every process via
    ``multihost_utils.broadcast_one_to_all``. The broadcast is a
    collective, so its completion doubles as an all-hosts-alive
    signal (``collective`` = True)."""

    collective = True

    @property
    def process_index(self) -> int:
        return jax.process_index()

    @property
    def num_processes(self) -> int:
        return jax.process_count()

    def broadcast(self, value):
        from jax.experimental import multihost_utils
        return multihost_utils.broadcast_one_to_all(value)

    def ack(self, seq: int) -> None:
        # The collective already synchronized every process; there is
        # no (and no need for a) backchannel.
        del seq

    def drain_acks(self):
        return []


class FakeTransport:
    """In-process stand-in for the multi-host broadcast: one queue per
    follower, plus a shared ack queue back to the publisher.

    ``endpoint(i)`` hands out the per-process view; endpoint 0
    publishes, endpoints 1..N-1 receive in their own threads. Tier-1
    tests drive the REAL bridge code (publish/worker_loop) over this,
    so follower step ordering, template agreement, and dead-follower
    detection are all pinned without subprocesses.
    """

    def __init__(self, num_processes: int):
        import queue
        if num_processes < 2:
            raise ValueError("FakeTransport needs >= 2 processes")
        self.num_processes = num_processes
        self._queues = [queue.Queue() for _ in range(num_processes)]
        self._acks: "queue.Queue" = queue.Queue()

    def endpoint(self, process_index: int) -> "_FakeEndpoint":
        return _FakeEndpoint(self, process_index)


class _FakeEndpoint:
    collective = False

    def __init__(self, transport: FakeTransport, process_index: int):
        self._transport = transport
        self.process_index = process_index
        self.num_processes = transport.num_processes
        # Follower receive timeout: generous enough for slow CI, small
        # enough that a wedged test fails instead of hanging forever.
        self.recv_timeout_s = 30.0

    def broadcast(self, value):
        if self.process_index == 0:
            for q in self._transport._queues[1:]:
                q.put(copy.deepcopy(value))
            return value
        item = self._transport._queues[self.process_index].get(
            timeout=self.recv_timeout_s)
        why = _template_mismatch(value, item)
        if why is not None:
            raise ValueError(
                f"follower {self.process_index} payload does not "
                f"match its receive template ({why}) — the "
                f"(kind, t, flags) header no longer derives the "
                f"payload shapes")
        return item

    def ack(self, seq: int) -> None:
        self._transport._acks.put(
            (self.process_index, seq, time.monotonic()))

    def drain_acks(self):
        import queue
        out = []
        while True:
            try:
                out.append(self._transport._acks.get_nowait())
            except queue.Empty:
                return out


class MultihostStepBridge:
    """Host-0 -> followers broadcast of per-step device-program inputs.

    Protocol per step: a fixed [kind, t_bucket, flags] int32 header,
    then the payload pytree whose array shapes are a pure function of
    (kind, t_bucket, flags) and the engine config — so followers can
    always offer a matching zero-filled structure to the endpoint's
    ``broadcast``. ``flags`` carries the presence of the optional
    per-request inputs (penalties, seeding, logprobs) whose keys are
    request-dependent rather than config-dependent.

    Rank 0 owns scheduling; followers mirror its dispatch sequence
    exactly. ``endpoint`` defaults to the real jax.distributed
    broadcast; tier-1 hands in ``FakeTransport`` endpoints.
    ``num_slices`` sizes the liveness ledger — processes map to
    slices contiguously (process grouping is slice-major, matching
    parallel/topology.py's device order).
    """

    FLAG_PENALTIES = 1
    FLAG_SEEDING = 2
    FLAG_LOGPROBS = 4
    FLAG_BIAS = 8
    FLAG_SUPPRESS = 16
    FLAG_GUIDED = 32

    def __init__(self, runner, endpoint=None, num_slices: int = 1,
                 liveness_timeout_s: float = 10.0):
        self.runner = runner
        self.endpoint = (endpoint if endpoint is not None
                         else JaxBroadcastEndpoint())
        self.num_slices = max(1, int(num_slices))
        self.liveness = SliceLiveness(self.num_slices,
                                      liveness_timeout_s)
        # Monotone per-publish sequence number; follower acks echo the
        # sequence they executed, so ordering bugs surface as stale
        # acks rather than silent divergence.
        self._seq = 0
        # Host 0 publishes from two threads (engine device loop:
        # prefill/decode; embed worker threads: KIND_EMBED). Followers
        # consume one strict header/payload/execute sequence, and XLA
        # collective programs must launch in the same order on every
        # process — so each publish+execute pair must be atomic.
        self.lock = threading.Lock()

    def slice_of_process(self, process_index: int) -> int:
        """Contiguous process -> slice mapping (slice-major hosts)."""
        n = max(1, getattr(self.endpoint, "num_processes", 1))
        return min(self.num_slices - 1,
                   process_index * self.num_slices // n)

    # -- shapes --------------------------------------------------------------

    def _payload_template(self, kind: int, t: int,
                          flags: int = 0) -> Dict[str, np.ndarray]:
        r = self.runner
        if kind == KIND_EMBED:
            # Embed batches have their own (batch_width, token-bucket)
            # geometry; every host built the same Embedder at startup.
            return {
                "tokens": np.zeros((r.embedder.batch_width, t),
                                   np.int32),
                "lengths": np.zeros((r.embedder.batch_width,),
                                    np.int32),
            }
        if kind == KIND_PREFILL:
            b, tt = r.prefill_width, t
        elif kind == KIND_SPEC:
            # Verify steps score t = speculative_k + 1 positions per
            # decode slot; t is static per engine config so the shape
            # is derivable from the header.
            b, tt = r.decode_width, t
        elif kind == KIND_UNIFIED:
            # Mixed ragged step (docs/unified_step.md): decode and
            # prefill rows share one [R, W] block; W rides the header
            # and the row count / draft span are config-static.
            b, tt = r.unified_rows, t
        else:
            b, tt = r.decode_width, 1
        template = {
            "tokens": np.zeros((b, tt), np.int32),
            "positions": np.zeros((b, tt), np.int32),
            "valid": np.zeros((b, tt), bool),
            "page_table": np.zeros((b, r.max_pages_per_seq), np.int32),
            "kv_lens": np.zeros((b,), np.int32),
            "last_index": np.zeros((b,), np.int32),
            "temperature": np.zeros((b,), np.float32),
            "top_p": np.zeros((b,), np.float32),
            "top_k": np.zeros((b,), np.int32),
            "rng": np.zeros((2,), np.uint32),
        }
        if kind == KIND_SPEC:
            # Draft tokens per row (-1 padded) + true draft lengths;
            # the acceptance rule runs in-graph (ops/sampling.py).
            template["drafts"] = np.zeros((b, t - 1), np.int32)
            template["draft_lens"] = np.zeros((b,), np.int32)
        if kind == KIND_UNIFIED:
            # Every unified row carries the draft span (zero-length
            # for prefill/plain-decode rows); width is config-static.
            template["drafts"] = np.zeros(
                (b, r.unified_span - 1), np.int32)
            template["draft_lens"] = np.zeros((b,), np.int32)
        if kind == KIND_DECODE and t > 1:
            # Decode bursts carry per-row lifecycle state
            # (model_runner.run_decode); STOP_SET_WIDTH is fixed so
            # this shape is derivable from the (kind, t) header alone.
            from production_stack_tpu.engine.model_runner import (
                STOP_SET_WIDTH,
            )
            template["active"] = np.zeros((b,), bool)
            template["budgets"] = np.zeros((b,), np.int32)
            template["stop_tokens"] = np.zeros(
                (b, STOP_SET_WIDTH), np.int32)
        if r.lora_registry is not None:
            template["lora_ids"] = np.zeros((b,), np.int32)
        if flags & self.FLAG_PENALTIES:
            v = r.config.model.vocab_size
            template["pen_counts"] = np.zeros((b, v), np.int32)
            template["pen_prompt_mask"] = np.zeros((b, v), bool)
            template["pen_presence"] = np.zeros((b,), np.float32)
            template["pen_frequency"] = np.zeros((b,), np.float32)
            template["pen_repetition"] = np.zeros((b,), np.float32)
        if flags & self.FLAG_SEEDING:
            template["seed_rows"] = np.zeros((b,), np.int32)
            template["seed_on"] = np.zeros((b,), bool)
            template["seed_emitted"] = np.zeros((b,), np.int32)
        if flags & self.FLAG_BIAS:
            template["logit_bias"] = np.zeros(
                (b, r.config.model.vocab_size), np.float32)
        if flags & self.FLAG_SUPPRESS:
            from production_stack_tpu.engine.model_runner import (
                STOP_SET_WIDTH,
            )
            template["sup_ids"] = np.zeros(
                (b, STOP_SET_WIDTH), np.int32)
            template["sup_rem"] = np.zeros((b,), np.int32)
        if flags & self.FLAG_GUIDED:
            # Followers hold identical automaton tables (built eagerly
            # at engine init — engine.py); only the per-row states
            # ride the broadcast.
            template["fsm_state"] = np.zeros((b,), np.int32)
        return template

    # -- host 0 --------------------------------------------------------------

    def publish(self, kind: int, t: int,
                payload: Dict[str, np.ndarray]) -> None:
        flags = 0
        if "pen_prompt_mask" in payload:
            flags |= self.FLAG_PENALTIES
        if "seed_rows" in payload:
            flags |= self.FLAG_SEEDING
        if payload.get("want_logprobs"):
            flags |= self.FLAG_LOGPROBS
        if "logit_bias" in payload:
            flags |= self.FLAG_BIAS
        if "sup_ids" in payload:
            flags |= self.FLAG_SUPPRESS
        if "fsm_state" in payload:
            flags |= self.FLAG_GUIDED
        header = np.asarray([kind, t, flags], np.int32)
        self.endpoint.broadcast(header)
        if kind != KIND_SHUTDOWN:
            # want_logprobs is a static python flag, carried in the
            # header (a non-array leaf can't ride the broadcast).
            arrays = {k: v for k, v in payload.items()
                      if k != "want_logprobs"}
            self.endpoint.broadcast(arrays)
        self._seq += 1
        if self.endpoint.collective:
            # broadcast_one_to_all returning means every process
            # participated — the strongest liveness signal available
            # without a backchannel.
            self.liveness.mark_all()
        else:
            # The publisher's own slice is trivially alive.
            self.liveness.heartbeat(self.slice_of_process(
                self.endpoint.process_index))
            self.pump_acks()

    def pump_acks(self) -> None:
        """Fold follower acks into the per-slice liveness ledger."""
        for process_index, _seq, when in self.endpoint.drain_acks():
            self.liveness.heartbeat(
                self.slice_of_process(process_index), when)

    def check_liveness(self) -> Dict[int, bool]:
        """Current slice_id -> live map (drains pending acks first).
        The /metrics per-slice gauges render exactly this."""
        self.pump_acks()
        return self.liveness.snapshot()

    def shutdown(self) -> None:
        """Release followers from their receive loop."""
        with self.lock:
            self.publish(KIND_SHUTDOWN, 0, {})

    # -- followers -----------------------------------------------------------

    def worker_loop(self) -> None:
        """Receive-execute loop for processes > 0. Returns on
        shutdown. Each executed step is acked with its sequence
        number so host 0's liveness ledger sees this process's slice
        making progress."""
        pid = self.endpoint.process_index
        logger.info("follower %d entering step loop", pid)
        seq = 0
        while True:
            header = self.endpoint.broadcast(np.zeros((3,), np.int32))
            kind, t, flags = (int(header[0]), int(header[1]),
                              int(header[2]))
            if kind == KIND_SHUTDOWN:
                logger.info("follower %d shutting down", pid)
                return
            payload = self.endpoint.broadcast(
                self._payload_template(kind, t, flags)
            )
            payload = {k: np.asarray(v) for k, v in payload.items()}
            if flags & self.FLAG_LOGPROBS:
                payload["want_logprobs"] = True
            self.runner.execute_payload(kind, payload, t)
            seq += 1
            self.endpoint.ack(seq)
