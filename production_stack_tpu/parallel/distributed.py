"""Multi-host serving: jax.distributed runtime + step-plan broadcast.

The reference's engines scale across hosts with vLLM's NCCL/Ray stack,
provisioned by the chart (/dev/shm, GPU resources —
deployment-vllm-multi.yaml:84-87,226-233). The TPU equivalent is JAX's
multi-controller model: every host of a slice runs this same program,
``jax.distributed.initialize`` wires the slice together, and jitted
steps over a global ``Mesh`` execute SPMD with XLA collectives riding
ICI (intra-slice) / DCN (inter-slice).

Serving needs one extra piece the SPMD model doesn't give us: the
scheduler (request queue, page allocator) lives only on host 0, but
every host must dispatch the SAME device program each step. The
``MultihostStepBridge`` closes that gap: host 0 authors a step payload
(numpy arrays) and broadcasts it; workers run a receive-execute loop.
All hosts then enter the same compiled step with identical inputs, so
the device programs line up without any per-step consensus protocol.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

import jax

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

KIND_SHUTDOWN = 0
KIND_PREFILL = 1
KIND_DECODE = 2
KIND_EMBED = 3  # /v1/embeddings|score|rerank batches (engine/embeddings.py)
KIND_SPEC = 4  # speculative verify step (docs/speculative.md)
KIND_UNIFIED = 5  # mixed ragged step (docs/unified_step.md)


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Join the jax.distributed runtime.

    On GKE TPU slices all arguments auto-detect from the TPU metadata;
    explicit values support bare-metal/CPU rigs (the reference's
    bare-metal flow analogue, run_production_stack/).
    """
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    logger.info("jax.distributed up: process %d/%d, %d local / %d "
                "global devices", jax.process_index(),
                jax.process_count(), jax.local_device_count(),
                jax.device_count())


def is_coordinator() -> bool:
    return jax.process_index() == 0


class MultihostStepBridge:
    """Host-0 -> workers broadcast of per-step device-program inputs.

    Protocol per step: a fixed [kind, t_bucket, flags] int32 header,
    then the payload pytree whose array shapes are a pure function of
    (kind, t_bucket, flags) and the engine config — so workers can
    always offer a matching zero-filled structure to
    ``broadcast_one_to_all``. ``flags`` carries the presence of the
    optional per-request inputs (penalties, seeding, logprobs) whose
    keys are request-dependent rather than config-dependent.
    """

    FLAG_PENALTIES = 1
    FLAG_SEEDING = 2
    FLAG_LOGPROBS = 4
    FLAG_BIAS = 8
    FLAG_SUPPRESS = 16
    FLAG_GUIDED = 32

    def __init__(self, runner):
        self.runner = runner
        # Host 0 publishes from two threads (engine device loop:
        # prefill/decode; embed worker threads: KIND_EMBED). Workers
        # consume one strict header/payload/execute sequence, and XLA
        # collective programs must launch in the same order on every
        # process — so each publish+execute pair must be atomic.
        import threading
        self.lock = threading.Lock()

    # -- shapes --------------------------------------------------------------

    def _payload_template(self, kind: int, t: int,
                          flags: int = 0) -> Dict[str, np.ndarray]:
        r = self.runner
        if kind == KIND_EMBED:
            # Embed batches have their own (batch_width, token-bucket)
            # geometry; every host built the same Embedder at startup.
            return {
                "tokens": np.zeros((r.embedder.batch_width, t),
                                   np.int32),
                "lengths": np.zeros((r.embedder.batch_width,),
                                    np.int32),
            }
        if kind == KIND_PREFILL:
            b, tt = r.prefill_width, t
        elif kind == KIND_SPEC:
            # Verify steps score t = speculative_k + 1 positions per
            # decode slot; t is static per engine config so the shape
            # is derivable from the header.
            b, tt = r.decode_width, t
        elif kind == KIND_UNIFIED:
            # Mixed ragged step (docs/unified_step.md): decode and
            # prefill rows share one [R, W] block; W rides the header
            # and the row count / draft span are config-static.
            b, tt = r.unified_rows, t
        else:
            b, tt = r.decode_width, 1
        template = {
            "tokens": np.zeros((b, tt), np.int32),
            "positions": np.zeros((b, tt), np.int32),
            "valid": np.zeros((b, tt), bool),
            "page_table": np.zeros((b, r.max_pages_per_seq), np.int32),
            "kv_lens": np.zeros((b,), np.int32),
            "last_index": np.zeros((b,), np.int32),
            "temperature": np.zeros((b,), np.float32),
            "top_p": np.zeros((b,), np.float32),
            "top_k": np.zeros((b,), np.int32),
            "rng": np.zeros((2,), np.uint32),
        }
        if kind == KIND_SPEC:
            # Draft tokens per row (-1 padded) + true draft lengths;
            # the acceptance rule runs in-graph (ops/sampling.py).
            template["drafts"] = np.zeros((b, t - 1), np.int32)
            template["draft_lens"] = np.zeros((b,), np.int32)
        if kind == KIND_UNIFIED:
            # Every unified row carries the draft span (zero-length
            # for prefill/plain-decode rows); width is config-static.
            template["drafts"] = np.zeros(
                (b, r.unified_span - 1), np.int32)
            template["draft_lens"] = np.zeros((b,), np.int32)
        if kind == KIND_DECODE and t > 1:
            # Decode bursts carry per-row lifecycle state
            # (model_runner.run_decode); STOP_SET_WIDTH is fixed so
            # this shape is derivable from the (kind, t) header alone.
            from production_stack_tpu.engine.model_runner import (
                STOP_SET_WIDTH,
            )
            template["active"] = np.zeros((b,), bool)
            template["budgets"] = np.zeros((b,), np.int32)
            template["stop_tokens"] = np.zeros(
                (b, STOP_SET_WIDTH), np.int32)
        if r.lora_registry is not None:
            template["lora_ids"] = np.zeros((b,), np.int32)
        if flags & self.FLAG_PENALTIES:
            v = r.config.model.vocab_size
            template["pen_counts"] = np.zeros((b, v), np.int32)
            template["pen_prompt_mask"] = np.zeros((b, v), bool)
            template["pen_presence"] = np.zeros((b,), np.float32)
            template["pen_frequency"] = np.zeros((b,), np.float32)
            template["pen_repetition"] = np.zeros((b,), np.float32)
        if flags & self.FLAG_SEEDING:
            template["seed_rows"] = np.zeros((b,), np.int32)
            template["seed_on"] = np.zeros((b,), bool)
            template["seed_emitted"] = np.zeros((b,), np.int32)
        if flags & self.FLAG_BIAS:
            template["logit_bias"] = np.zeros(
                (b, r.config.model.vocab_size), np.float32)
        if flags & self.FLAG_SUPPRESS:
            from production_stack_tpu.engine.model_runner import (
                STOP_SET_WIDTH,
            )
            template["sup_ids"] = np.zeros(
                (b, STOP_SET_WIDTH), np.int32)
            template["sup_rem"] = np.zeros((b,), np.int32)
        if flags & self.FLAG_GUIDED:
            # Workers hold identical automaton tables (built eagerly
            # at engine init — engine.py); only the per-row states
            # ride the broadcast.
            template["fsm_state"] = np.zeros((b,), np.int32)
        return template

    # -- host 0 --------------------------------------------------------------

    def publish(self, kind: int, t: int,
                payload: Dict[str, np.ndarray]) -> None:
        from jax.experimental import multihost_utils
        flags = 0
        if "pen_prompt_mask" in payload:
            flags |= self.FLAG_PENALTIES
        if "seed_rows" in payload:
            flags |= self.FLAG_SEEDING
        if payload.get("want_logprobs"):
            flags |= self.FLAG_LOGPROBS
        if "logit_bias" in payload:
            flags |= self.FLAG_BIAS
        if "sup_ids" in payload:
            flags |= self.FLAG_SUPPRESS
        if "fsm_state" in payload:
            flags |= self.FLAG_GUIDED
        header = np.asarray([kind, t, flags], np.int32)
        multihost_utils.broadcast_one_to_all(header)
        if kind != KIND_SHUTDOWN:
            # want_logprobs is a static python flag, carried in the
            # header (a non-array leaf can't ride the broadcast).
            arrays = {k: v for k, v in payload.items()
                      if k != "want_logprobs"}
            multihost_utils.broadcast_one_to_all(arrays)

    def shutdown(self) -> None:
        """Release workers from their receive loop."""
        with self.lock:
            self.publish(KIND_SHUTDOWN, 0, {})

    # -- workers -------------------------------------------------------------

    def worker_loop(self) -> None:
        """Receive-execute loop for hosts > 0. Returns on shutdown."""
        from jax.experimental import multihost_utils
        logger.info("worker %d entering step loop", jax.process_index())
        while True:
            header = multihost_utils.broadcast_one_to_all(
                np.zeros((3,), np.int32)
            )
            kind, t, flags = (int(header[0]), int(header[1]),
                              int(header[2]))
            if kind == KIND_SHUTDOWN:
                logger.info("worker %d shutting down",
                            jax.process_index())
                return
            payload = multihost_utils.broadcast_one_to_all(
                self._payload_template(kind, t, flags)
            )
            payload = {k: np.asarray(v) for k, v in payload.items()}
            if flags & self.FLAG_LOGPROBS:
                payload["want_logprobs"] = True
            self.runner.execute_payload(kind, payload, t)
