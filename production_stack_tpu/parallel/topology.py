"""Topology discovery + declarative mesh planning (``MeshPlan``).

The flat ``build_mesh`` grid reshape (parallel/mesh.py) assumed every
device is one ICI hop from every other — true on a single slice,
false the moment a deployment spans slices (multislice TPU) or hosts
(CPU rigs, the forced-device CI harness). This module makes the mesh
*topology-aware*:

- ``discover_topology`` groups devices into **slices** (ICI domains):
  TPU ``slice_index`` coords when the runtime exposes them, process
  grouping otherwise, and an explicit ``num_slices`` override so the
  ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` CPU harness
  can rehearse multi-slice layouts in CI.
- ``MeshPlan`` is the declarative replacement for the positional
  ``build_mesh`` arguments: axis sizes plus per-axis *placement*
  ("ici" = must not straddle a slice boundary, "any" = may cross
  slices over DCN). The plan validates against the discovered
  topology at build time, so ``tp`` straddling a slice boundary is a
  config-time ``ValueError``, not a silent DCN-slow collective.
- The slice-as-replica rule falls out of the device order: slices
  concatenate slice-major and ``dp`` is the outermost axis, so with
  ``dp == num_slices`` each data-parallel replica IS one slice and
  only ``dp`` traffic (none, for serving) crosses DCN.

``parallel.mesh.build_mesh`` delegates here and keeps its signature —
existing callers get topology validation for free.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Mapping, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

# Mesh axis order, outermost first: tp innermost so tensor-parallel
# collectives ride adjacent ICI links; sp ring hops next; pp stage
# hops cross the slowest dimension; dp (pure replication) outermost
# so a replica maps onto a contiguous — ideally whole-slice — device
# block.
AXIS_ORDER: Tuple[str, ...] = ("dp", "pp", "sp", "tp")

# Default per-axis placement: tensor-parallel and the sp ring want
# every hop on ICI; pipeline hops and replica fan-out tolerate DCN.
DEFAULT_PLACEMENT: Dict[str, str] = {
    "dp": "any",
    "pp": "any",
    "sp": "ici",
    "tp": "ici",
}

# Forced slice count for rigs where discovery has nothing to go on
# (the CI harness: one process, N fake CPU devices). CLI surface is
# --num-slices (engine/server.py); the env var serves bare pytest.
_FAKE_SLICES_ENV = "PSTPU_NUM_SLICES"


@dataclasses.dataclass(frozen=True)
class DeviceTopology:
    """Devices grouped into ICI domains ("slices"), slice-major.

    ``source`` records how the grouping was derived: "ici" (TPU
    slice_index coords), "process" (one slice per host process),
    "forced" (explicit num_slices override), or "flat" (no grouping
    signal — one slice).
    """

    slices: Tuple[Tuple[object, ...], ...]
    source: str = "flat"

    @property
    def num_slices(self) -> int:
        return len(self.slices)

    @property
    def slice_size(self) -> int:
        return len(self.slices[0]) if self.slices else 0

    @property
    def devices(self) -> Tuple[object, ...]:
        return tuple(d for s in self.slices for d in s)

    @property
    def num_devices(self) -> int:
        return sum(len(s) for s in self.slices)

    def slice_of(self, device) -> int:
        for i, group in enumerate(self.slices):
            if any(d is device or d == device for d in group):
                return i
        raise ValueError(f"device {device!r} not in this topology")

    def describe(self) -> str:
        return (f"{self.num_devices} devices in {self.num_slices} "
                f"slice(s) of {self.slice_size} ({self.source})")


def discover_topology(devices: Optional[Sequence] = None,
                      num_slices: int = 0) -> DeviceTopology:
    """Group ``devices`` (default: ``jax.devices()``) into slices.

    Precedence: an explicit ``num_slices`` (or the PSTPU_NUM_SLICES
    env var) forces an even contiguous split — the CI harness's fake
    multislice; otherwise TPU ``slice_index`` attributes group real
    multislice deployments; otherwise multiple process indices group
    one slice per host; otherwise everything is one flat slice.
    """
    devices = (list(jax.devices()) if devices is None
               else list(devices))
    if not devices:
        raise ValueError("discover_topology needs at least one device")
    if num_slices <= 0:
        num_slices = int(os.environ.get(_FAKE_SLICES_ENV, "0") or 0)
    if num_slices > 0:
        n = len(devices)
        if num_slices > n or n % num_slices:
            raise ValueError(
                f"num_slices={num_slices} must evenly divide the "
                f"{n} visible devices")
        size = n // num_slices
        return DeviceTopology(
            tuple(tuple(devices[i * size:(i + 1) * size])
                  for i in range(num_slices)),
            source="forced")
    slice_ids = [getattr(d, "slice_index", None) for d in devices]
    if all(s is not None for s in slice_ids) and len(set(slice_ids)) > 1:
        groups: Dict[int, list] = {}
        for d, s in zip(devices, slice_ids):
            groups.setdefault(int(s), []).append(d)
        return DeviceTopology(
            tuple(tuple(groups[s]) for s in sorted(groups)),
            source="ici")
    procs = [getattr(d, "process_index", 0) for d in devices]
    if len(set(procs)) > 1:
        pgroups: Dict[int, list] = {}
        for d, p in zip(devices, procs):
            pgroups.setdefault(int(p), []).append(d)
        return DeviceTopology(
            tuple(tuple(pgroups[p]) for p in sorted(pgroups)),
            source="process")
    return DeviceTopology((tuple(devices),), source="flat")


def parse_placement(text: str) -> Dict[str, str]:
    """Parse a ``--mesh-placement`` override: "tp=ici,pp=any,...".

    "auto" (or empty) keeps :data:`DEFAULT_PLACEMENT`. Unknown axis
    names and placement values are rejected loudly.
    """
    placement = dict(DEFAULT_PLACEMENT)
    if not text or text == "auto":
        return placement
    for entry in text.split(","):
        axis, _, where = entry.strip().partition("=")
        if axis not in AXIS_ORDER:
            raise ValueError(
                f"mesh_placement axis {axis!r} unknown "
                f"(axes: {'/'.join(AXIS_ORDER)})")
        if where not in ("ici", "any"):
            raise ValueError(
                f"mesh_placement for {axis!r} must be 'ici' or 'any' "
                f"(got {where!r})")
        placement[axis] = where
    return placement


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """Declarative mesh: axis sizes + per-axis placement.

    Field reference lives in docs/parallelism.md (staticcheck's
    config-contract keeps the two in sync). ``placement`` maps axis
    name -> "ici" (the axis's contiguous device block must fit inside
    one slice) or "any" (may span slices over DCN).
    """

    tp: int = 1
    dp: int = 1
    pp: int = 1
    sp: int = 1
    placement: Mapping[str, str] = dataclasses.field(
        default_factory=lambda: dict(DEFAULT_PLACEMENT))

    def __post_init__(self):
        for axis in AXIS_ORDER:
            if getattr(self, axis) < 1:
                raise ValueError(f"MeshPlan.{axis} must be >= 1")
        for axis, where in self.placement.items():
            if axis not in AXIS_ORDER:
                raise ValueError(
                    f"MeshPlan placement axis {axis!r} unknown "
                    f"(axes: {'/'.join(AXIS_ORDER)})")
            if where not in ("ici", "any"):
                raise ValueError(
                    f"MeshPlan placement for {axis!r} must be 'ici' "
                    f"or 'any' (got {where!r})")

    @property
    def axis_sizes(self) -> Dict[str, int]:
        return {axis: getattr(self, axis) for axis in AXIS_ORDER}

    @property
    def num_devices(self) -> int:
        return self.dp * self.pp * self.sp * self.tp

    def _inner_block(self, axis: str) -> int:
        """Contiguous device-block length axis ``axis`` spans: its own
        size times every axis inner to it (device order is row-major
        over AXIS_ORDER, so inner axes vary fastest)."""
        sizes = self.axis_sizes
        block = 1
        for a in reversed(AXIS_ORDER):
            block *= sizes[a]
            if a == axis:
                return block
        raise ValueError(f"unknown axis {axis!r}")

    def validate(self, topology: DeviceTopology) -> None:
        """Reject plans the discovered topology cannot carry."""
        if self.num_devices > topology.num_devices:
            raise ValueError(
                f"MeshPlan needs {self.num_devices} devices, "
                f"topology has {topology.num_devices} "
                f"({topology.describe()})")
        sizes = {len(s) for s in topology.slices}
        if len(sizes) > 1:
            raise ValueError(
                "MeshPlan needs equal-size slices "
                f"(got sizes {sorted(sizes)})")
        slice_size = topology.slice_size
        for axis in AXIS_ORDER:
            where = self.placement.get(
                axis, DEFAULT_PLACEMENT[axis])
            if where != "ici" or getattr(self, axis) == 1:
                continue
            block = self._inner_block(axis)
            if block > slice_size or slice_size % block:
                raise ValueError(
                    f"MeshPlan axis '{axis}' (size "
                    f"{getattr(self, axis)}, contiguous block "
                    f"{block}) would straddle a slice boundary: "
                    f"slices are {slice_size} devices wide "
                    f"({topology.describe()}). Shrink the axis or "
                    f"place it 'any' to allow DCN hops.")

    def build(self, topology: Optional[DeviceTopology] = None) -> Mesh:
        """Validate against ``topology`` (default: discovered) and
        build the ``(dp, pp, sp, tp)`` mesh over slice-major devices —
        so ``dp == num_slices`` makes each replica one slice."""
        if topology is None:
            topology = discover_topology()
        self.validate(topology)
        grid = np.asarray(
            topology.devices[: self.num_devices], dtype=object
        ).reshape(self.dp, self.pp, self.sp, self.tp)
        return Mesh(grid, axis_names=AXIS_ORDER)
