"""Context-parallel SERVING prefill: one dispatch, sequence over ``sp``.

The reference has no sequence/context parallelism (SURVEY.md §2.6);
its long-context story is flag pass-through to vLLM. The standalone
ring-attention forward (parallel/context.py) proved the math in rounds
1-2 but was unreachable from the engine. This module implements the
ENGINE's prefill contract over the ``sp`` mesh axis, so
``--context-parallel-size N`` is a real serving flag
(engine/server.py):

- A long prompt prefills in ONE device program instead of a chunk
  loop: tokens shard [B, T/n] per device, attention runs as ring
  attention (ops/ring_attention.py — K/V hop the ring via ppermute
  over ICI, flash-style online softmax), everything else is local.
- The paged KV cache stays REPLICATED across sp: each layer
  all-gathers the freshly computed K/V shards (T x kv x d — small
  next to the O(T^2) attention the ring just distributed) and every
  device performs the identical ``write_to_pages`` scatter, so after
  prefill any shard can serve the decode steps on the standard
  engine path ("decode on the owning shard").
- Padding rows to T % sp == 0 carry valid=False; their KV writes land
  on the trash page (ops/attention.write_to_pages) and their ring
  outputs are discarded.
- Only the final hidden state leaves the body sharded; the LM-head
  matmul runs once on the [B, H] last-token rows outside shard_map —
  logits for T tokens are never materialized.

Scope: llama-family (llama/mistral/qwen2) + gpt2 architectures,
first-touch prompts (no prefix-cache hit). sp composes with tp
(round-5): weights enter the shard_map with their GSPMD layouts
(parallel/mesh.py param_specs — column projections sliced over 'tp'),
each device runs its local heads through the ring, and the
row-parallel matmuls (wo / w_down / fc2) finish with an explicit
``psum`` over 'tp' — the same collective GSPMD inserts on the
decode path, so sp x tp prefill and plain-tp decode agree bit-for-bit
on the replicated activations. sp also composes with dp (replicated
batch rows); pp composition is still rejected loudly by the
model_runner gate.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from production_stack_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from production_stack_tpu.engine.config import ModelConfig
from production_stack_tpu.models.llama import (
    _layer_param_names,
    rms_norm,
)
from production_stack_tpu.models.gpt2 import (
    GPT2_LAYER_NAMES,
    layer_norm,
)
from production_stack_tpu.ops.attention import write_to_pages
from production_stack_tpu.ops.ring_attention import ring_attention
from production_stack_tpu.ops.rope import apply_rope
from production_stack_tpu.parallel.pipeline_serving import (
    _lora_mm,
    _stage_layer,
)

Params = Dict[str, jnp.ndarray]

# llama body covers llama/mistral/qwen2; gpt2 has its own layer body
# (learned positions, LayerNorm, biased projections, gelu MLP — the
# round-3 "second family" widening).
SP_FAMILIES = ("llama", "mistral", "qwen2", "gpt2")


def shard_w_forward(forward, mesh: Mesh):
    """Wrap the engine forward so multi-token dispatches shard their W
    (token) axis over ``sp``.

    The cp runner's unified ragged step (docs/unified_step.md) and
    spec-verify program route through the PLAIN forward — without a
    constraint GSPMD replicates the whole [R, W] block on every ring
    device. Pinning tokens/positions/valid to P(None, 'sp') makes the
    partitioner split the W axis (QK^T's query axis — parallel, not a
    reduction), so the math and therefore the greedy byte stream are
    unchanged while each device computes W/sp columns. Single-token
    decode dispatches (W == 1) pass through unsharded — nothing to
    split."""
    from jax.sharding import NamedSharding

    from production_stack_tpu.parallel.mesh import _on_mesh

    w_sharding = NamedSharding(mesh, _on_mesh(P(None, "sp"), mesh))

    def wrapped(params, config, tokens, positions, page_table,
                kv_lens, valid, k_cache, v_cache,
                lora=None, lora_ids=None):
        if tokens.shape[1] > 1:
            constrain = (
                lambda x: jax.lax.with_sharding_constraint(
                    x, w_sharding))
            tokens = constrain(tokens)
            positions = constrain(positions)
            valid = constrain(valid)
        return forward(params, config, tokens, positions, page_table,
                       kv_lens, valid, k_cache, v_cache,
                       lora=lora, lora_ids=lora_ids)

    return wrapped


def sp_prefill_forward(params: Params, config: ModelConfig,
                       tokens: jnp.ndarray, page_table: jnp.ndarray,
                       valid: jnp.ndarray, last_index: jnp.ndarray,
                       k_cache: jnp.ndarray, v_cache: jnp.ndarray,
                       lora=None, lora_ids=None,
                       *, mesh: Mesh,
                       ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Whole-prompt prefill with the sequence sharded over ``sp``.

    Args:
      tokens:     [B, T] prompt tokens, T % sp == 0 (runner pads)
      page_table: [B, max_pages] physical pages for the whole prompt
      valid:      [B, T] mask of real tokens (False = padding)
      last_index: [B] index of each prompt's final token
      k/v_cache:  [L, kv, pages, d, page_size], replicated over sp
      lora:       optional adapter stacks (engine/lora.py) — the LoRA
                  delta is a per-row map over tokens, so sequence
                  sharding passes through it untouched; under tp each
                  target shards like its base projection (row-parallel
                  targets shard A's input axis so x@A stays a local
                  partial the existing psum closes; column-parallel
                  targets shard B's output axis). Round-5 widening.
      lora_ids:   [B] adapter slot per batch row (0 = base model)

    Returns (row_logits [B, vocab] at last_index, new_k, new_v).
    """
    from production_stack_tpu.parallel.mesh import (
        _on_mesh,
        param_specs,
    )

    # A caller-built mesh may carry only an 'sp' axis (build_mesh
    # always has all four): without 'tp', weights stay replicated and
    # the psums are skipped entirely.
    has_tp = "tp" in mesh.axis_names
    tp = mesh.shape["tp"] if has_tp else 1
    nh, nkv, d = (config.num_attention_heads // tp,
                  config.num_key_value_heads // tp, config.head_dim)
    b, t = tokens.shape
    gpt2 = config.architecture == "gpt2"
    layer_names = (GPT2_LAYER_NAMES if gpt2
                   else _layer_param_names(config))
    layer_params = {k: params[k] for k in layer_names}
    shared = {k: v for k, v in params.items() if k not in layer_names}
    # Weights keep their serving GSPMD layouts inside the shard_map
    # (no resharding at the boundary): column-parallel projections are
    # 'tp' slices, so the body below works on nh/nkv LOCAL heads and
    # closes each row-parallel matmul with a psum over 'tp'.
    specs = param_specs(config)

    def on_mesh(spec: P) -> P:
        return _on_mesh(spec, mesh)

    def psum_tp(x):
        return jax.lax.psum(x, "tp") if has_tp else x

    def llama_layer(x, lp_i, ll, ids, sc, positions_l):
        bl, tl = positions_l.shape
        a_in = rms_norm(x, lp_i["attn_norm"], config.rms_norm_eps)
        q = _lora_mm(a_in, lp_i["wq"], ll, "wq", ids, sc)
        k = _lora_mm(a_in, lp_i["wk"], ll, "wk", ids, sc)
        v = _lora_mm(a_in, lp_i["wv"], ll, "wv", ids, sc)
        if config.attention_bias:
            q, k, v = (q + lp_i["bq"], k + lp_i["bk"],
                       v + lp_i["bv"])
        q = apply_rope(q.reshape(bl, tl, nh, d), positions_l,
                       config.rope_theta)
        k = apply_rope(k.reshape(bl, tl, nkv, d), positions_l,
                       config.rope_theta)
        v = v.reshape(bl, tl, nkv, d)
        return x, q, k, v

    def llama_post(x, attn, lp_i, ll, ids, sc):
        bl, tl = attn.shape[:2]
        # wo / w_down are row-parallel ('tp' slices of the input dim):
        # each device holds a partial sum until the psum.
        x = x + psum_tp(
            _lora_mm(attn.reshape(bl, tl, nh * d), lp_i["wo"], ll,
                     "wo", ids, sc))
        m_in = rms_norm(x, lp_i["mlp_norm"], config.rms_norm_eps)
        return x + psum_tp(
            _lora_mm(
                jax.nn.silu(_lora_mm(m_in, lp_i["w_gate"], ll,
                                     "w_gate", ids, sc))
                * _lora_mm(m_in, lp_i["w_up"], ll, "w_up", ids, sc),
                lp_i["w_down"], ll, "w_down", ids, sc))

    def gpt2_layer(x, lp_i, ll, ids, sc, positions_l):
        bl, tl = positions_l.shape
        a_in = layer_norm(x, lp_i["attn_norm_w"], lp_i["attn_norm_b"])
        q = (_lora_mm(a_in, lp_i["wq"], ll, "wq", ids, sc)
             + lp_i["bq"]).reshape(bl, tl, nh, d)
        k = (_lora_mm(a_in, lp_i["wk"], ll, "wk", ids, sc)
             + lp_i["bk"]).reshape(bl, tl, nkv, d)
        v = (_lora_mm(a_in, lp_i["wv"], ll, "wv", ids, sc)
             + lp_i["bv"]).reshape(bl, tl, nkv, d)
        return x, q, k, v

    def gpt2_post(x, attn, lp_i, ll, ids, sc):
        bl, tl = attn.shape[:2]
        # Row-parallel wo/fc2 close with a psum; their biases are
        # replicated and must be added exactly once (after the psum).
        x = x + (psum_tp(
            _lora_mm(attn.reshape(bl, tl, nh * d), lp_i["wo"], ll,
                     "wo", ids, sc))
            + lp_i["bo"])
        m_in = layer_norm(x, lp_i["mlp_norm_w"], lp_i["mlp_norm_b"])
        hidden = jax.nn.gelu(
            _lora_mm(m_in, lp_i["fc1"], ll, "fc1", ids, sc)
            + lp_i["fc1_b"], approximate=True)
        return x + (psum_tp(_lora_mm(hidden, lp_i["fc2"], ll, "fc2",
                                     ids, sc))
                    + lp_i["fc2_b"])

    qkv_fn, post_fn = ((gpt2_layer, gpt2_post) if gpt2
                       else (llama_layer, llama_post))

    lora_ab = (None if lora is None
               else {"a": lora["a"], "b": lora["b"]})
    lora_scale = (None if lora is None
                  else lora["scaling"][lora_ids])

    def body(lp, shared_p, kc, vc, tokens_l, valid_l, page_table,
             lora_ab, lora_ids, lora_scale):
        idx = jax.lax.axis_index("sp")
        bl, tl = tokens_l.shape
        positions_l = idx * tl + jnp.broadcast_to(
            jnp.arange(tl)[None, :], (bl, tl))
        # Global (replicated) views for the page writes.
        positions_full = jnp.broadcast_to(
            jnp.arange(t)[None, :], (b, t))
        valid_full = jax.lax.all_gather(
            valid_l, "sp", axis=1, tiled=True)

        x = shared_p["embed"][tokens_l]
        if gpt2:
            # Learned positions are indexed by GLOBAL position, so
            # each shard embeds its own offset range.
            x = x + shared_p["pos_embed"][positions_l]

        # Static loop over layers, in-place cache scatters at a
        # static index (see models.llama.forward).
        for layer in range(config.num_hidden_layers):
            lp_i = _stage_layer(lp, layer)
            ll = (None if lora_ab is None
                  else jax.tree.map(lambda s: s[layer], lora_ab))
            x, q, k, v = qkv_fn(x, lp_i, ll, lora_ids, lora_scale,
                                positions_l)
            # O(T^2) mixing distributed around the ring; K/V shards
            # stay put, blocks rotate via ppermute.
            attn = ring_attention(q, k, v, "sp")
            # The cache is replicated: gather the full-sequence K/V
            # (linear in T) and do the identical scatter everywhere.
            k_full = jax.lax.all_gather(k, "sp", axis=1, tiled=True)
            v_full = jax.lax.all_gather(v, "sp", axis=1, tiled=True)
            kc = write_to_pages(kc, k_full, page_table,
                                positions_full, valid_full,
                                layer=layer)
            vc = write_to_pages(vc, v_full, page_table,
                                positions_full, valid_full,
                                layer=layer)
            x = post_fn(x, attn, lp_i, ll, lora_ids, lora_scale)
        if gpt2:
            return (layer_norm(x, shared_p["final_norm_w"],
                               shared_p["final_norm_b"]), kc, vc)
        return (rms_norm(x, shared_p["final_norm"],
                         config.rms_norm_eps), kc, vc)

    repl = P()
    # KV cache shards its head axis over 'tp' (parallel/mesh.py
    # cache_spec): each device scatters the K/V heads it computed.
    # QuantKV caches carry a pytree spec — the 4-D scale leaf drops
    # the (always-replicated) head_dim entry, congruent with how
    # shard_cache places the two leaves.
    cache_sp = on_mesh(P(None, "tp", None, None, None))
    from production_stack_tpu.ops.quant_kv import QuantKV
    if isinstance(k_cache, QuantKV):
        cache_sp = QuantKV(cache_sp,
                           P(*cache_sp[:3], cache_sp[4]))
    def lp_spec(k):
        spec = on_mesh(specs.get(k, repl))
        if isinstance(layer_params[k], tuple):
            # int8 (weight [L, in, out], scale [L, out]): the scale
            # follows the weight's layer + output-channel axes
            # (mirrors parallel/mesh.py shard_params).
            return (spec, P(spec[0], spec[2]))
        return spec

    # Adapter stacks replicate over sp (layers local everywhere);
    # under tp each target shards like its base projection — the ONE
    # sharding rule shared with pp x tp (engine/lora.py
    # lora_stack_specs).
    if lora_ab is None:
        lora_ab_spec = repl
    else:
        from production_stack_tpu.engine.lora import lora_stack_specs
        lora_ab_spec = lora_stack_specs(lora_ab, None, on_mesh)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=({k: lp_spec(k) for k in layer_params},
                  {k: on_mesh(specs.get(k, repl)) for k in shared},
                  cache_sp, cache_sp, P(None, "sp"), P(None, "sp"),
                  repl, lora_ab_spec, repl, repl),
        out_specs=(P(None, "sp", None), cache_sp, cache_sp),
        check_vma=False,
    )
    hidden, new_k, new_v = fn(layer_params, shared, k_cache, v_cache,
                              tokens, valid, page_table,
                              lora_ab, lora_ids, lora_scale)
    # LM head on the last-token rows only (B x H @ H x V).
    last_h = hidden[jnp.arange(b), last_index]
    head = shared.get("lm_head")
    if head is None:
        head = shared["embed"].T
    return (last_h @ head).astype(jnp.float32), new_k, new_v
