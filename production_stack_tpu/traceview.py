"""Cross-hop trace stitching: merge router + engine span logs into a
per-request text waterfall.

The router (router/tracing.py) and every engine (engine/tracing.py)
each write their own ``--request-span-log`` JSON lines. One
disaggregated request therefore leaves up to three span lines — the
router's ``"span": "request"`` record and one ``"span":
"engine_request"`` record per hop (prefill role, decode role) — all
keyed by the router's ``x-request-id``. This module merges those files
offline into one time-ordered waterfall per request:

    $ python -m production_stack_tpu.traceview router.jsonl \\
          prefill-engine.jsonl decode-engine.jsonl --request-id ID

Stitching is pure timestamp arithmetic on the span records: engine
event lines carry absolute ``ts`` values, and the router span's
derived millisecond fields (queue_delay_ms, handoff_ms, ttft_ms,
latency_ms) are re-anchored onto its ``arrival_ts``. Clocks are
assumed to come from the same host family (the test rig runs all
parties in one process); cross-machine skew shows up as out-of-order
rows, not a crash.

Importable pieces — ``load_spans``, ``stitch``, ``render_waterfall`` —
are reused by the golden-merge test (tests/test_traceview.py).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple


def load_spans(paths: List[str]) -> List[dict]:
    """Parse span JSON lines from ``paths``; non-span lines (plain log
    text, partial writes) are skipped, not fatal."""
    spans: List[dict] = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                # Spans may ride inside ordinary log lines when the
                # sink is "-": recover the JSON object by its brace.
                start = line.find("{")
                if start < 0:
                    continue
                try:
                    obj = json.loads(line[start:])
                except ValueError:
                    continue
                if isinstance(obj, dict) and obj.get("span") in (
                        "request", "engine_request"):
                    spans.append(obj)
    return spans


def _router_rows(span: dict) -> List[Tuple[float, str, str, str]]:
    """The router span's derived ms fields, re-anchored to absolute
    times: (ts, source, event, details) rows."""
    t0 = span["arrival_ts"]
    rows = [(t0, "router", "arrival",
             f"path={span.get('path')} model={span.get('model')}")]

    def at(ms_field: str, event: str, details: str = "") -> None:
        ms = span.get(ms_field)
        if ms is not None:
            rows.append((t0 + ms / 1e3, "router", event, details))

    at("queue_delay_ms", "routed",
       f"backend={span.get('backend')}" + (
           f" retries={span['retries']}" if span.get("retries") else ""))
    if span.get("prefill_backend") is not None:
        # The prefill hop has no own ms field; its completion is the
        # decode hop's route time minus handoff_ms.
        q, h = span.get("queue_delay_ms"), span.get("handoff_ms")
        if q is not None and h is not None:
            rows.append((t0 + (q - h) / 1e3, "router", "prefill_hop_done",
                         f"prefill_backend={span['prefill_backend']} "
                         f"handoff_ms={h}"))
    at("ttft_ms", "first_chunk")
    at("latency_ms", "finish",
       f"status={span.get('status')} chunks={span.get('chunks')}")
    return rows


def _engine_rows(span: dict) -> List[Tuple[float, str, str, str]]:
    role = span.get("role", "?")
    src = f"engine[{role} {span.get('seq_id')}]"
    rows = []
    for ev in span.get("events", []):
        extras = {k: v for k, v in ev.items() if k not in ("event", "ts")}
        details = " ".join(f"{k}={v}" for k, v in sorted(extras.items()))
        rows.append((ev["ts"], src, ev["event"], details))
    return rows


def stitch(spans: List[dict], request_id: str) -> List[dict]:
    """All spans belonging to ``request_id``, router span first."""
    mine = [s for s in spans if s.get("request_id") == request_id]
    return sorted(mine, key=lambda s: s.get("span") != "request")


def render_waterfall(spans: List[dict], request_id: str) -> str:
    """One text waterfall for ``request_id`` over stitched ``spans``."""
    mine = stitch(spans, request_id)
    if not mine:
        return f"no spans for request {request_id}\n"
    rows: List[Tuple[float, str, str, str]] = []
    for span in mine:
        rows.extend(_router_rows(span) if span["span"] == "request"
                    else _engine_rows(span))
    rows.sort(key=lambda r: r[0])
    t0 = rows[0][0]
    src_w = max(len(r[1]) for r in rows)
    ev_w = max(len(r[2]) for r in rows)
    out = [f"request {request_id}  ({len(mine)} spans)"]
    for ts, src, event, details in rows:
        out.append(f"  t+{(ts - t0) * 1e3:9.2f}ms  {src:<{src_w}}  "
                   f"{event:<{ev_w}}  {details}".rstrip())
    return "\n".join(out) + "\n"


def load_slow_archive(path: str) -> List[dict]:
    """Spans from a saved ``GET /debug/slow`` payload (or a bare entry
    list): every archived exemplar already carries its stitched
    ``spans``, so the file renders without any span-log files."""
    with open(path) as f:
        payload = json.load(f)
    entries = (payload.get("entries", [])
               if isinstance(payload, dict) else payload)
    spans: List[dict] = []
    for entry in entries:
        if isinstance(entry, dict):
            spans.extend(s for s in entry.get("spans", [])
                         if isinstance(s, dict))
    return spans


def _request_ids(spans: List[dict]) -> List[str]:
    seen: Dict[str, None] = {}
    for s in spans:
        rid = s.get("request_id")
        if rid is not None:
            seen.setdefault(rid, None)
    return list(seen)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m production_stack_tpu.traceview",
        description="Merge router + engine span logs into per-request "
                    "waterfalls (docs/observability.md)")
    parser.add_argument("logs", nargs="*",
                        help="Span JSON-line files (router and/or "
                             "engine --request-span-log outputs)")
    parser.add_argument("--request-id", default=None,
                        help="Render only this request (default: every "
                             "request id found, in first-seen order)")
    parser.add_argument("--from-slow-archive", default=None,
                        help="Render spans from a saved GET /debug/slow "
                             "JSON payload instead of (or merged with) "
                             "span-log files")
    args = parser.parse_args(argv)
    if not args.logs and not args.from_slow_archive:
        parser.error("need span-log files and/or --from-slow-archive")
    spans = load_spans(args.logs)
    if args.from_slow_archive:
        spans.extend(load_slow_archive(args.from_slow_archive))
    ids = ([args.request_id] if args.request_id
           else _request_ids(spans))
    if not ids:
        print("no spans found", file=sys.stderr)
        return 1
    for rid in ids:
        sys.stdout.write(render_waterfall(spans, rid))
    return 0


if __name__ == "__main__":
    sys.exit(main())
