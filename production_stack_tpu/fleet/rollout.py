"""Canary-scored rolling upgrades with automatic rollback.

Changing a pool's ``revision`` in the fleet spec does not restart
anything in place.  The :class:`RolloutController` (owned by the
:class:`~production_stack_tpu.fleet.manager.FleetManager`, ticked at
the top of every reconcile pass) walks the pool through a surge
rolling update:

1. **canary** — one extra replica is spawned at the target revision
   (the surge, so stable capacity never dips) and promoted LIVE;
2. **bake** — the canary takes ``rollout.canary_weight`` of the
   pool's dispatch traffic while the stable set serves the rest;
3. **judge** — at the end of the bake window the canary is scored
   against the router's own sensors: the 5m SLO burn rate, the
   perf-drift sentinel, the canary's crash streak, its breaker
   failure count, and its p99 latency vs the worst stable replica;
4. **roll** — a passing canary continues the roll one replica at a
   time (spawn-new, then drain-old in ``migrate`` mode: the old
   replica's checkpointed streams are proactively resumed on a
   new-revision replica via ``POST /v1/resume`` — byte-exact
   zero-loss even for multi-minute streams);
5. **rollback** — a failing canary is migrate-drained, the old
   revision is respawned, and the rollout freezes behind a latched
   alarm gauge until an operator intervenes
   (``--rollout-cmd pause|resume|abort``, docs/fleet.md).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, Optional

from production_stack_tpu.fleet.autoscaler import parse_prometheus_text
from production_stack_tpu.fleet.spec import PoolSpec, RevisionSpec
from production_stack_tpu.router.services.metrics_service import (
    rollout_alarm,
    rollout_phase,
    rollout_replicas,
    rollout_rollbacks,
)
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

# Lifecycle of one pool's rollout; "paused" and "rolled_back" hold
# whatever surge the underlying phase had so capacity stays stable.
ROLLOUT_PHASES = ("idle", "canary", "bake", "roll", "paused",
                  "rolled_back")


@dataclass
class _PoolRollout:
    """Controller state for one pool."""

    phase: str = "idle"
    target: Optional[RevisionSpec] = None
    paused_from: str = "idle"
    bake_start: float = -1.0
    baseline_errors: float = 0.0
    crashes: int = 0
    rollbacks: int = 0
    alarm: bool = False
    verdict: str = ""
    # Revision keys an operator aborted; never retried until the spec
    # names a different target.
    abandoned: set = field(default_factory=set)


class RolloutController:
    """Drives every pool's revision rollout from the reconcile loop."""

    def __init__(self, manager):
        self._mgr = manager
        self._state: Dict[str, _PoolRollout] = {
            p.name: _PoolRollout() for p in manager.spec.pools}
        self._last_cmd_ts = 0.0

    # ---- hooks the manager reads every reconcile pass ---------------------

    def surge(self, pool_name: str) -> int:
        """Extra replicas (over the autoscaler's desired count) this
        pool should run right now so the rollout never eats stable
        capacity."""
        st = self._state[pool_name]
        phase = st.paused_from if st.phase == "paused" else st.phase
        if phase in ("canary", "bake"):
            return 1
        if phase == "roll":
            olds = [r for r in self._mgr.replicas[pool_name]
                    if st.target is not None
                    and r.rev_key != st.target.key()
                    and r.state != "draining"]
            return 1 if olds else 0
        return 0

    def revision_for_spawn(self, pool: PoolSpec) -> RevisionSpec:
        """Which revision a new replica of *pool* should run: the
        rollout target while rolling (and for the single canary while
        baking — a crashed canary respawns at the target, a crashed
        stable replica at the current revision)."""
        st = self._state[pool.name]
        phase = st.paused_from if st.phase == "paused" else st.phase
        if st.target is not None:
            if phase == "roll":
                return st.target
            if phase in ("canary", "bake"):
                key = st.target.key()
                n_target = sum(
                    1 for r in self._mgr.replicas[pool.name]
                    if r.rev_key == key and r.state != "draining")
                if n_target == 0:
                    return st.target
        return self._mgr.current_revision[pool.name]

    def target_key(self, pool_name: str) -> Optional[tuple]:
        st = self._state[pool_name]
        return st.target.key() if st.target is not None else None

    def canary_weights(self) -> Dict[str, float]:
        """url -> dispatch traffic share, for the router's dynamic
        config.  Only baking canaries are weighted; once the roll is
        on, new-revision replicas are ordinary pool members."""
        out: Dict[str, float] = {}
        for pool in self._mgr.spec.pools:
            st = self._state[pool.name]
            phase = st.paused_from if st.phase == "paused" else st.phase
            if phase != "bake":
                continue
            canary = self._canary(pool.name)
            if canary is not None and canary.state == "live":
                out[canary.url] = pool.rollout.canary_weight
        return out

    def status(self) -> Dict[str, dict]:
        """Per-pool rollout snapshot shipped to the router via the
        dynamic config (stacktop renders it; docs/fleet.md)."""
        out: Dict[str, dict] = {}
        for pool in self._mgr.spec.pools:
            st = self._state[pool.name]
            if (st.phase == "idle" and not st.alarm
                    and st.rollbacks == 0):
                continue
            out[pool.name] = {
                "phase": st.phase,
                "current_build":
                    self._mgr.current_revision[pool.name].build_id,
                "target_build":
                    st.target.build_id if st.target else "",
                "alarm": st.alarm,
                "rollbacks": st.rollbacks,
                "verdict": st.verdict,
            }
        return out

    # ---- internals --------------------------------------------------------

    def _canary(self, pool_name: str):
        st = self._state[pool_name]
        if st.target is None:
            return None
        key = st.target.key()
        for replica in self._mgr.replicas[pool_name]:
            if replica.rev_key == key and replica.state != "draining":
                return replica
        return None

    async def _fetch_metrics(self) -> str:
        url = self._mgr.spec.router_url
        if not url:
            return ""
        try:
            session = await self._mgr._http()
            async with session.get(
                    url.rstrip("/") + "/metrics") as resp:
                return await resp.text()
        except Exception as e:
            logger.warning("rollout judge cannot scrape router "
                           "metrics: %s", e)
            return ""

    async def _server_errors(self, server_url: str) -> float:
        for name, labels, value in parse_prometheus_text(
                await self._fetch_metrics()):
            if (name == "vllm:server_errors_total"
                    and labels.get("server") == server_url):
                return value
        return 0.0

    async def _judge(self, pool: PoolSpec, st: _PoolRollout,
                     canary) -> Optional[str]:
        """Score the canary at the end of its bake window.  Returns a
        failure reason, or None when every enabled signal passes."""
        spec = pool.rollout
        if (spec.max_crash_streak > 0
                and st.crashes >= spec.max_crash_streak):
            return (f"canary crashed {st.crashes}x "
                    f"(limit {spec.max_crash_streak})")
        text = await self._fetch_metrics()
        burn_5m = -1.0
        drift_tripped = []
        errors = -1.0
        ttft = {}
        itl = {}
        for name, labels, value in parse_prometheus_text(text):
            if (name == "vllm:slo_burn_rate"
                    and labels.get("window") == "5m"):
                burn_5m = value
            elif name == "vllm:perf_drift" and value > 0:
                drift_tripped.append(labels.get("phase", "?"))
            elif (name == "vllm:server_errors_total"
                  and labels.get("server") == canary.url):
                errors = value
            elif name == "vllm:ttft_p99_seconds":
                ttft[labels.get("server", "")] = value
            elif name == "vllm:itl_p99_seconds":
                itl[labels.get("server", "")] = value
        if (spec.max_slo_burn_rate_5m > 0
                and burn_5m > spec.max_slo_burn_rate_5m):
            return (f"5m SLO burn rate {burn_5m:.2f} > "
                    f"{spec.max_slo_burn_rate_5m:.2f}")
        if spec.fail_on_perf_drift and drift_tripped:
            return f"perf drift tripped: {sorted(drift_tripped)}"
        if spec.max_server_errors > 0 and errors >= 0:
            delta = errors - st.baseline_errors
            if delta > spec.max_server_errors:
                return (f"router charged canary with {delta:.0f} "
                        f"failures (limit {spec.max_server_errors:.0f})")
        if spec.max_latency_ratio > 0:
            stable_urls = {
                r.url for r in self._mgr.replicas[pool.name]
                if r is not canary and r.state == "live"}
            for label, series in (("ttft", ttft), ("itl", itl)):
                canary_p99 = series.get(canary.url, -1.0)
                stable_p99 = max(
                    [series[u] for u in stable_urls
                     if series.get(u, -1.0) > 0] or [-1.0])
                if canary_p99 > 0 and stable_p99 > 0:
                    ratio = canary_p99 / stable_p99
                    if ratio > spec.max_latency_ratio:
                        return (f"canary {label} p99 {ratio:.2f}x the "
                                f"worst stable replica (limit "
                                f"{spec.max_latency_ratio:.2f}x)")
        return None

    async def _rollback(self, pool: PoolSpec, st: _PoolRollout,
                        reason: str) -> None:
        st.verdict = reason
        st.rollbacks += 1
        st.alarm = True
        st.phase = "rolled_back"
        logger.error(
            "pool %s: rolling back revision %r: %s (alarm latched; "
            "--rollout-cmd resume to retry, abort to abandon)",
            pool.name, st.target.build_id if st.target else "", reason)
        migrate = pool.rollout.drain_mode == "migrate"
        key = st.target.key() if st.target is not None else None
        for replica in list(self._mgr.replicas[pool.name]):
            if (key is not None and replica.rev_key == key
                    and replica.state != "draining"):
                await self._mgr._start_drain(replica, migrate=migrate)

    def _poll_control(self) -> Optional[dict]:
        path = self._mgr.spec.rollout_control_path
        if not path or not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                raw = json.load(f)
        except Exception:
            return None
        ts = float(raw.get("ts", 0.0))
        if ts <= self._last_cmd_ts:
            return None
        self._last_cmd_ts = ts
        return raw

    async def _apply_command(self, cmd: dict) -> bool:
        """pause/resume/abort from the fleet CLI (docs/fleet.md)."""
        action = cmd.get("cmd")
        only = cmd.get("pool")
        changed = False
        for pool in self._mgr.spec.pools:
            if only and pool.name != only:
                continue
            st = self._state[pool.name]
            applied = False
            if action == "pause":
                if st.phase in ("canary", "bake", "roll"):
                    st.paused_from = st.phase
                    st.phase = "paused"
                    applied = True
            elif action == "resume":
                if st.phase == "paused":
                    st.phase = st.paused_from
                    applied = True
                elif st.phase == "rolled_back":
                    # Unlatch and retry the rollout from the top.
                    st.alarm = False
                    st.phase = "idle"
                    st.target = None
                    applied = True
            elif action == "abort":
                if st.target is not None:
                    st.abandoned.add(st.target.key())
                if st.phase in ("canary", "bake", "roll", "paused"):
                    # Walk back any new-revision surplus.
                    key = (st.target.key()
                           if st.target is not None else None)
                    migrate = pool.rollout.drain_mode == "migrate"
                    for replica in list(self._mgr.replicas[pool.name]):
                        if (key is not None and replica.rev_key == key
                                and replica.state != "draining"):
                            await self._mgr._start_drain(
                                replica, migrate=migrate)
                st.alarm = False
                st.phase = "idle"
                st.target = None
                applied = True
            if applied:
                changed = True
                logger.warning("pool %s: rollout command %r applied "
                               "(phase now %s)", pool.name, action,
                               st.phase)
        return changed

    def _refresh_gauges(self) -> None:
        for pool in self._mgr.spec.pools:
            st = self._state[pool.name]
            for phase in ROLLOUT_PHASES:
                rollout_phase.labels(
                    pool=pool.name, phase=phase).set(
                        1.0 if phase == st.phase else 0.0)
            by_rev: Dict[str, int] = {}
            for replica in self._mgr.replicas[pool.name]:
                rev = replica.build_id or "unversioned"
                by_rev[rev] = by_rev.get(rev, 0) + 1
            for rev, count in by_rev.items():
                rollout_replicas.labels(
                    pool=pool.name, revision=rev).set(count)
            rollout_rollbacks.labels(pool=pool.name).set(st.rollbacks)
            rollout_alarm.labels(pool=pool.name).set(
                1.0 if st.alarm else 0.0)

    # ---- the tick ----------------------------------------------------------

    async def tick(self) -> bool:
        """One controller pass; returns True when the router's
        dynamic config must be rewritten (membership metadata, canary
        weights or rollout status changed)."""
        changed = False
        cmd = self._poll_control()
        if cmd is not None:
            changed |= await self._apply_command(cmd)
        for pool in self._mgr.spec.pools:
            changed |= await self._tick_pool(pool)
        self._refresh_gauges()
        return changed

    async def _tick_pool(self, pool: PoolSpec) -> bool:
        st = self._state[pool.name]
        if st.phase in ("paused", "rolled_back"):
            return False
        changed = False
        mgr = self._mgr
        target = pool.revision
        current = mgr.current_revision[pool.name]

        if st.phase == "idle":
            if (pool.rollout.enable
                    and target.key() != current.key()
                    and target.key() not in st.abandoned):
                st.phase = "canary"
                st.target = target
                st.crashes = 0
                st.verdict = ""
                logger.info(
                    "pool %s: rollout %r -> %r starting (canary "
                    "surge)", pool.name, current.build_id,
                    target.build_id)
                changed = True
            return changed

        # The spec's target moved mid-rollout: restart from the top.
        if st.target is not None and target.key() != st.target.key():
            logger.warning(
                "pool %s: rollout target changed mid-flight; "
                "restarting rollout", pool.name)
            st.phase = "idle"
            st.target = None
            return True

        if st.phase == "canary":
            canary = self._canary(pool.name)
            if canary is not None and canary.state == "live":
                payload = await mgr._probe_health(canary) or {}
                reported = payload.get("build_id", "")
                if (st.target.build_id and reported
                        and reported != st.target.build_id):
                    await self._rollback(
                        pool, st,
                        f"canary reports build {reported!r}, wanted "
                        f"{st.target.build_id!r}")
                    return True
                st.phase = "bake"
                st.bake_start = mgr._clock()
                st.baseline_errors = await self._server_errors(
                    canary.url)
                logger.info(
                    "pool %s: canary %s live at build %r; baking "
                    "%.0fs at weight %.2f", pool.name, canary.url,
                    st.target.build_id, pool.rollout.bake_s,
                    pool.rollout.canary_weight)
                changed = True
            return changed

        if st.phase == "bake":
            canary = self._canary(pool.name)
            if canary is None or canary.process.poll() is not None:
                st.crashes += 1
                if (pool.rollout.max_crash_streak > 0
                        and st.crashes
                        >= pool.rollout.max_crash_streak):
                    await self._rollback(
                        pool, st,
                        f"canary crashed {st.crashes}x (limit "
                        f"{pool.rollout.max_crash_streak})")
                else:
                    # Reconcile respawns the canary at the target
                    # revision; re-enter bake once it is LIVE again.
                    st.phase = "canary"
                return True
            if mgr._clock() - st.bake_start >= pool.rollout.bake_s:
                reason = await self._judge(pool, st, canary)
                if reason is None:
                    st.phase = "roll"
                    st.verdict = "passed"
                    logger.info(
                        "pool %s: canary passed; rolling revision %r "
                        "across the pool", pool.name,
                        st.target.build_id)
                else:
                    await self._rollback(pool, st, reason)
                return True
            return False

        if st.phase == "roll":
            key = st.target.key()
            replicas = mgr.replicas[pool.name]
            olds = [r for r in replicas if r.rev_key != key]
            if not olds:
                mgr.current_revision[pool.name] = st.target
                st.phase = "idle"
                st.target = None
                logger.info(
                    "pool %s: rollout complete; every replica on "
                    "build %r",
                    pool.name,
                    mgr.current_revision[pool.name].build_id)
                return True
            draining_olds = [r for r in olds if r.state == "draining"]
            live_total = sum(1 for r in replicas if r.state == "live")
            if (not draining_olds
                    and live_total > mgr.desired[pool.name]):
                # One at a time: drain the oldest old-revision replica
                # only while spare LIVE capacity covers it.
                victim = min(
                    (r for r in olds if r.state == "live"),
                    key=lambda r: r.port, default=None)
                if victim is not None:
                    migrate = pool.rollout.drain_mode == "migrate"
                    logger.info(
                        "pool %s: rolling %s off build %r (%s drain)",
                        pool.name, victim.url, victim.build_id,
                        "migrate" if migrate else "wait")
                    await mgr._start_drain(victim, migrate=migrate)
                    return True
            return False
        return False
