"""SLO autoscaler: router metrics -> desired replicas per pool.

Pure target tracking, deliberately boring: the desired count is
``ceil(current * ratio)`` for the worst observed/target ratio across
enabled signals, with a hysteresis dead-band so noise inside
``tolerance`` of the target never scales, and per-direction cooldowns
so a breach can't flap the pool.  Prefill and decode pools each get
their own :class:`PoolAutoscaler`, so they scale independently.

Signals come from the router's aggregated ``/metrics`` exposition
(one scrape covers the whole fleet): per-server ``vllm:ttft_p99_seconds``
/ ``vllm:itl_p99_seconds`` (request stats), ``vllm:num_requests_waiting``
and ``vllm:engine_gpu_cache_usage_perc`` (engine-authoritative), and
``vllm:engine_disagg_awaiting_kv_requests`` for decode pools fed by
prefill handoffs.
"""

from __future__ import annotations

import math
import re
import time
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from production_stack_tpu.fleet.spec import AutoscalerSpec, PoolSpec
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

_SAMPLE_RE = re.compile(
    r"^(?P<name>[A-Za-z_:][A-Za-z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>[^\s]+)")
_LABEL_RE = re.compile(r'(\w+)="([^"]*)"')


def parse_prometheus_text(
        text: str) -> Iterable[Tuple[str, Dict[str, str], float]]:
    """Yields (metric name, labels, value) from an exposition body."""
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        labels = dict(_LABEL_RE.findall(m.group("labels") or ""))
        yield m.group("name"), labels, value


@dataclass
class PoolSignals:
    """Aggregated per-pool observations for one autoscale tick."""

    ttft_p99_s: float = -1.0   # worst replica
    itl_p99_s: float = -1.0    # worst replica
    waiting: float = -1.0      # summed across replicas
    cache_usage: float = -1.0  # worst replica
    awaiting_kv: float = -1.0  # summed across replicas
    # Fleet-wide: the router's vllm:slo_burn_rate{window="5m"} gauge
    # has no server label, so every pool sees the same value.
    slo_burn_rate: float = -1.0
    # Phase-time histogram means (docs/autotuning.md): the pool-split
    # controller biases the prefill-vs-decode replica split on the
    # ratio of these, riding the same one-scrape signal path.
    prefill_time_mean_s: float = -1.0  # worst replica
    decode_time_mean_s: float = -1.0   # worst replica

    def _max(self, attr: str, value: float) -> None:
        setattr(self, attr, max(getattr(self, attr), value))

    def _sum(self, attr: str, value: float) -> None:
        current = getattr(self, attr)
        setattr(self, attr, value + (current if current >= 0 else 0.0))


# metric name -> (PoolSignals attr, aggregation across replicas)
_SIGNAL_METRICS = {
    "vllm:ttft_p99_seconds": ("ttft_p99_s", "max"),
    "vllm:itl_p99_seconds": ("itl_p99_s", "max"),
    "vllm:num_requests_waiting": ("waiting", "sum"),
    "vllm:engine_gpu_cache_usage_perc": ("cache_usage", "max"),
    "vllm:engine_disagg_awaiting_kv_requests": ("awaiting_kv", "sum"),
    "vllm:engine_request_prefill_time_mean_seconds":
        ("prefill_time_mean_s", "max"),
    "vllm:engine_request_decode_time_mean_seconds":
        ("decode_time_mean_s", "max"),
}


def signals_from_router_metrics(
        text: str, url_to_pool: Dict[str, str]) -> Dict[str, PoolSignals]:
    """Groups the router's per-server gauges into per-pool signals.

    ``url_to_pool`` maps each replica's base URL (the router's
    ``server`` label) to its pool name; servers the fleet manager does
    not own are ignored.
    """
    out: Dict[str, PoolSignals] = {
        pool: PoolSignals() for pool in set(url_to_pool.values())}
    for name, labels, value in parse_prometheus_text(text):
        if name == "vllm:slo_burn_rate":
            # SLO-ledger burn (docs/observability.md): no server label
            # — a fleet-wide signal mirrored into every pool. Only the
            # fast 5m window drives scaling; the 1h window is for
            # paging, not capacity.
            if labels.get("window") == "5m" and value >= 0:
                for signals in out.values():
                    signals._max("slo_burn_rate", value)
            continue
        target = _SIGNAL_METRICS.get(name)
        if target is None:
            continue
        pool = url_to_pool.get(labels.get("server", ""))
        if pool is None or value < 0:
            continue  # -1 means "no observation yet", not zero load
        attr, agg = target
        signals = out[pool]
        (signals._max if agg == "max" else signals._sum)(attr, value)
    return out


class PoolAutoscaler:
    """Target tracking with hysteresis and cooldowns for one pool."""

    def __init__(self, pool: PoolSpec,
                 clock: Callable[[], float] = time.monotonic):
        self.pool = pool
        self.spec: AutoscalerSpec = pool.autoscaler
        self._clock = clock
        self._last_scale_up = -math.inf
        self._last_scale_down = -math.inf

    def _ratios(self, current: int,
                signals: PoolSignals) -> List[Tuple[str, float]]:
        spec = self.spec
        out: List[Tuple[str, float]] = []
        if spec.target_ttft_p99_s > 0 and signals.ttft_p99_s >= 0:
            out.append(("ttft_p99",
                        signals.ttft_p99_s / spec.target_ttft_p99_s))
        if spec.target_itl_p99_s > 0 and signals.itl_p99_s >= 0:
            out.append(("itl_p99",
                        signals.itl_p99_s / spec.target_itl_p99_s))
        if spec.target_waiting_per_replica > 0 and signals.waiting >= 0:
            per_replica = signals.waiting / max(1, current)
            out.append(("waiting",
                        per_replica / spec.target_waiting_per_replica))
        if spec.target_cache_usage > 0 and signals.cache_usage >= 0:
            out.append(("cache_usage",
                        signals.cache_usage / spec.target_cache_usage))
        if spec.target_awaiting_kv > 0 and signals.awaiting_kv >= 0:
            per_replica = signals.awaiting_kv / max(1, current)
            out.append(("awaiting_kv",
                        per_replica / spec.target_awaiting_kv))
        if spec.target_slo_burn_rate > 0 and signals.slo_burn_rate >= 0:
            out.append(("slo_burn_rate",
                        signals.slo_burn_rate
                        / spec.target_slo_burn_rate))
        return out

    def desired(self, current: int,
                signals: Optional[PoolSignals]) -> int:
        """Desired replica count given the current count and signals.

        Stateful: applying a change here starts the matching cooldown.
        Callers must pass the count of replicas that serve traffic
        (live, not draining).
        """
        low = self.pool.min_replicas
        high = self.pool.max_replicas
        clamped = min(high, max(low, current))
        if not self.spec.enable or signals is None:
            return clamped
        ratios = self._ratios(current, signals)
        if not ratios:
            return clamped
        driver, ratio = max(ratios, key=lambda kv: kv[1])
        now = self._clock()
        if ratio > 1.0 + self.spec.tolerance:
            want = min(high, max(clamped, math.ceil(current * ratio)))
            if want > clamped:
                if now - self._last_scale_up < self.spec.scale_up_cooldown_s:
                    return clamped
                logger.info(
                    "pool %s: scale up %d -> %d (%s ratio %.2f)",
                    self.pool.name, current, want, driver, ratio)
                self._last_scale_up = now
                return want
        elif ratio < 1.0 - self.spec.tolerance:
            want = max(low, min(clamped, math.ceil(current * ratio)))
            if want < clamped:
                # Scale-down waits out both cooldowns: shrinking right
                # after an expansion would thrash the very replicas the
                # breach just bought.
                last = max(self._last_scale_up, self._last_scale_down)
                if now - last < self.spec.scale_down_cooldown_s:
                    return clamped
                logger.info(
                    "pool %s: scale down %d -> %d (%s ratio %.2f)",
                    self.pool.name, current, want, driver, ratio)
                self._last_scale_down = now
                return want
        return clamped
