"""Reconciler-owned engine lifecycle.

The :class:`FleetManager` makes observed state (spawned engine
processes) converge on the declarative :class:`FleetSpec`:

- spawn: allocate a port from the fleet range, start the engine
  server process, and register it with the router (rewrite the
  dynamic-config JSON the router's ``DynamicConfigWatcher`` polls)
  only once its ``/health`` answers;
- scale: an SLO autoscaler per pool turns router metrics into a
  desired replica count; prefill and decode pools move independently;
- drain (zero-loss scale-down): deregister the replica first so the
  router stops routing to it, then ``POST /drain {"exit": true}`` —
  the engine rejects new admissions with 503 + Retry-After, finishes
  every in-flight sequence, and exits itself.  The reconciler only
  escalates to SIGTERM after ``drain_timeout_s`` *and* only while the
  replica reports zero active requests; it never SIGKILLs an engine
  with running sequences.
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

import aiohttp

from production_stack_tpu.fleet.autoscaler import (
    PoolAutoscaler,
    PoolSignals,
    signals_from_router_metrics,
)
from production_stack_tpu.fleet.spec import (
    FleetSpec,
    PoolSpec,
    RevisionSpec,
)
from production_stack_tpu.router.services.metrics_service import (
    fleet_crash_respawns,
    fleet_desired_replicas,
    fleet_live_replicas,
    fleet_scale_events,
)
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

STARTING = "starting"
LIVE = "live"
DRAINING = "draining"

# Grace between "drained replica reports idle but ignored SIGTERM"
# and SIGKILL.  Only ever reached with zero running sequences.
_SIGKILL_GRACE_S = 10.0


@dataclass
class Replica:
    """One spawned engine process and its lifecycle state."""

    pool: str
    port: int
    url: str
    process: subprocess.Popen
    state: str = STARTING
    drain_started: float = -1.0
    sigterm_sent: float = -1.0
    # The revision this replica was spawned at (docs/fleet.md) and
    # whether its drain runs in migrate mode (checkpointed streams
    # proactively resumed elsewhere instead of waited out).
    build_id: str = ""
    rev_key: tuple = ()
    migrate: bool = False


class FleetManager:
    """Reconcile + autoscale loops over a :class:`FleetSpec`."""

    def __init__(self, spec: FleetSpec,
                 clock: Callable[[], float] = time.monotonic):
        self.spec = spec
        self._clock = clock
        self._pools: Dict[str, PoolSpec] = {p.name: p for p in spec.pools}
        self.replicas: Dict[str, List[Replica]] = {
            p.name: [] for p in spec.pools}
        self.desired: Dict[str, int] = {
            p.name: p.min_replicas for p in spec.pools}
        self.autoscalers: Dict[str, PoolAutoscaler] = {
            p.name: PoolAutoscaler(p, clock) for p in spec.pools}
        self._session: Optional[aiohttp.ClientSession] = None
        self._stopping = False
        # Crash-loop containment (docs/crash_recovery.md): recent
        # non-drain exit times per pool (breaker window), consecutive
        # crashes since the last healthy promotion (backoff exponent),
        # the earliest clock a respawn is allowed, and a latch so the
        # open breaker is logged once per trip, not every tick.
        self._crash_times: Dict[str, deque] = {
            p.name: deque() for p in spec.pools}
        self._crash_streak: Dict[str, int] = {
            p.name: 0 for p in spec.pools}
        self._next_spawn_ok: Dict[str, float] = {
            p.name: 0.0 for p in spec.pools}
        self._breaker_logged: Dict[str, bool] = {
            p.name: False for p in spec.pools}
        # Revision each pool is currently rolled out at.  Spawns use
        # this (so a crash respawn never jumps revisions mid-bake);
        # the rollout controller moves it to ``pool.revision`` only
        # once a roll completes (docs/fleet.md).
        self.current_revision: Dict[str, RevisionSpec] = {
            p.name: p.revision for p in spec.pools}
        from production_stack_tpu.fleet.rollout import RolloutController
        self.rollout = RolloutController(self)
        # Self-tuning pool split (docs/autotuning.md): biases the
        # prefill-vs-decode replica split after the per-pool
        # autoscalers have spoken. Spec-gated, off by default.
        self.pool_split = None
        if spec.autotune_pool_split:
            from production_stack_tpu.autotune.fleet import (
                PoolSplitController)
            self.pool_split = PoolSplitController(clock=clock)

    # ---- plumbing ---------------------------------------------------------

    async def _http(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=5.0))
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    def _alloc_port(self) -> int:
        used = {r.port for reps in self.replicas.values() for r in reps}
        for port in range(self.spec.port_start, self.spec.port_end + 1):
            if port not in used:
                return port
        raise RuntimeError(
            f"fleet port range [{self.spec.port_start}, "
            f"{self.spec.port_end}] exhausted")

    def _command(self, pool: PoolSpec, port: int,
                 revision: RevisionSpec) -> List[str]:
        if pool.command:
            argv = [c.format(port=port, model=pool.model, role=pool.role)
                    for c in pool.command]
        else:
            argv = [sys.executable, "-m",
                    "production_stack_tpu.engine.server",
                    "--model", pool.model, "--host", "127.0.0.1",
                    "--port", str(port), "--engine-role", pool.role]
            argv += list(pool.engine_flags)
        # Revision surface rides last so a revision can override the
        # pool's base flags; --build-id makes membership verifiable at
        # /health and /version on both engine variants.
        argv += list(revision.engine_flags)
        if revision.build_id:
            argv += ["--build-id", revision.build_id]
        return argv

    async def _probe_health(self, replica: Replica) -> Optional[dict]:
        status, payload = await self._probe_health_raw(replica)
        return payload if status == 200 else None

    async def _probe_health_raw(self, replica: Replica):
        """(HTTP status, payload) of ``GET /health`` — the payload is
        returned even for a 503, so drain escalation can tell a
        watchdog-wedged engine from a merely busy one.  (None, None)
        when the replica is unreachable."""
        try:
            session = await self._http()
            async with session.get(replica.url + "/health") as resp:
                try:
                    payload = await resp.json()
                except Exception:
                    payload = None
                return resp.status, payload
        except Exception:
            return None, None

    # ---- registration -----------------------------------------------------

    def _write_router_config(self) -> None:
        """Rewrites the dynamic-config JSON with the LIVE membership.

        Atomic (tmp + rename) so the watcher never reads a torn file;
        draining and still-starting replicas are excluded, which is
        the primary mechanism keeping new work off a draining engine.
        """
        path = self.spec.router_config_path
        if not path:
            return
        backends: List[str] = []
        models: List[str] = []
        roles: List[str] = []
        revisions: List[str] = []
        migrating: List[str] = []
        for pool in self.spec.pools:
            for replica in self.replicas[pool.name]:
                if replica.state == LIVE:
                    backends.append(replica.url)
                    models.append(pool.model)
                    roles.append(pool.role)
                    revisions.append(replica.build_id)
                elif replica.state == DRAINING and replica.migrate:
                    # Migrate-mode drains: the router classifies these
                    # engines' mid-stream deaths as planned migrations
                    # (resume outcome "migrated", no poison blame).
                    migrating.append(replica.url)
        payload = {
            "service_discovery": "static",
            "routing_logic": self.spec.routing_logic,
            "static_backends": backends,
            "static_models": models,
            "static_roles": roles,
            "static_revisions": revisions,
            "canary_weights": self.rollout.canary_weights(),
            "migrating": migrating,
            "rollout_status": self.rollout.status(),
        }
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=2)
        os.replace(tmp, path)

    def _refresh_gauges(self) -> None:
        for pool in self.spec.pools:
            live = sum(1 for r in self.replicas[pool.name]
                       if r.state == LIVE)
            fleet_desired_replicas.labels(pool=pool.name).set(
                self.desired[pool.name])
            fleet_live_replicas.labels(pool=pool.name).set(live)

    # ---- reconcile --------------------------------------------------------

    def _spawn(self, pool: PoolSpec,
               revision: Optional[RevisionSpec] = None) -> Replica:
        if revision is None:
            revision = self.rollout.revision_for_spawn(pool)
        port = self._alloc_port()
        argv = self._command(pool, port, revision)
        process = subprocess.Popen(argv, stdout=subprocess.DEVNULL)
        replica = Replica(pool=pool.name, port=port,
                          url=f"http://127.0.0.1:{port}", process=process,
                          build_id=revision.build_id,
                          rev_key=revision.key())
        self.replicas[pool.name].append(replica)
        logger.info("pool %s: spawned replica %s (pid %d, build %r)",
                    pool.name, replica.url, process.pid,
                    revision.build_id)
        return replica

    async def _start_drain(self, replica: Replica,
                           migrate: bool = False) -> None:
        replica.state = DRAINING
        replica.drain_started = self._clock()
        replica.migrate = migrate
        # Deregister before asking the engine to drain: the router must
        # stop choosing this replica before it starts 503ing admissions.
        self._write_router_config()
        try:
            session = await self._http()
            async with session.post(
                    replica.url + "/drain",
                    json={"exit": True, "migrate": migrate}) as resp:
                await resp.read()
        except Exception as e:
            logger.warning("pool %s: drain request to %s failed: %s",
                           replica.pool, replica.url, e)

    async def _escalate_drain(self, replica: Replica) -> None:
        """Post-timeout escalation. Never kills a busy engine — unless
        its watchdog has tripped: a wedged device step will never
        reach idle, and waiting on it would wedge the whole rollout
        behind one stuck replica."""
        timeout = self.spec.drain_timeout_s
        if timeout <= 0:
            return
        if self._clock() - replica.drain_started < timeout:
            return
        _, payload = await self._probe_health_raw(replica)
        wedged = (payload or {}).get("status") == "watchdog"
        if (payload is not None and payload.get("active_requests")
                and not wedged):
            logger.warning(
                "pool %s: %s still has %s in-flight past the %.0fs drain "
                "timeout; waiting (never killing a busy engine)",
                replica.pool, replica.url,
                payload.get("active_requests"), timeout)
            return
        if wedged:
            logger.warning(
                "pool %s: %s is watchdog-wedged while draining "
                "(stuck %.1fs); escalating despite %s in-flight",
                replica.pool, replica.url,
                (payload or {}).get("stuck_step_s", 0.0),
                (payload or {}).get("active_requests", 0))
        if replica.sigterm_sent < 0:
            logger.warning("pool %s: %s idle but did not exit after "
                           "drain; sending SIGTERM",
                           replica.pool, replica.url)
            replica.process.terminate()
            replica.sigterm_sent = self._clock()
        elif self._clock() - replica.sigterm_sent > _SIGKILL_GRACE_S:
            logger.error("pool %s: %s ignored SIGTERM while idle; "
                         "killing", replica.pool, replica.url)
            replica.process.kill()

    def _record_crash(self, pool: PoolSpec) -> None:
        """A replica exited without a drain: advance the backoff and
        the breaker window."""
        now = self._clock()
        self._crash_times[pool.name].append(now)
        streak = self._crash_streak[pool.name] + 1
        self._crash_streak[pool.name] = streak
        backoff = min(
            pool.respawn_backoff_base_s * (2 ** (streak - 1)),
            pool.respawn_backoff_max_s)
        # Jitter downward only: pools of replicas dying together must
        # not respawn in lockstep, and the cap stays a true cap.
        backoff *= random.uniform(0.5, 1.0)
        self._next_spawn_ok[pool.name] = now + backoff

    def _spawn_allowed(self, pool: PoolSpec) -> bool:
        """Crash-loop gate: exponential backoff between respawns, and
        a per-pool breaker that stops respawning entirely while the
        pool has crashed ``crash_loop_threshold`` times inside
        ``crash_loop_window_s`` (a broken image or poison traffic —
        more copies of it will not help)."""
        now = self._clock()
        crashes = self._crash_times[pool.name]
        while crashes and now - crashes[0] > pool.crash_loop_window_s:
            crashes.popleft()
        if (pool.crash_loop_threshold > 0
                and len(crashes) >= pool.crash_loop_threshold):
            if not self._breaker_logged[pool.name]:
                logger.error(
                    "pool %s: crash-loop breaker open (%d crashes in "
                    "%.0fs); pausing respawns until the window cools",
                    pool.name, len(crashes), pool.crash_loop_window_s)
                self._breaker_logged[pool.name] = True
            return False
        self._breaker_logged[pool.name] = False
        return now >= self._next_spawn_ok[pool.name]

    async def reconcile_once(self) -> None:
        """One convergence pass: reap, promote, drain, spawn."""
        changed = False
        # The rollout controller moves first: it reads last pass's
        # replica states, sets per-pool surge counts and the revision
        # new spawns should run, and starts migrate-drains.
        changed |= await self.rollout.tick()
        for pool in self.spec.pools:
            replicas = self.replicas[pool.name]

            for replica in list(replicas):
                if replica.process.poll() is None:
                    continue
                if replica.state != DRAINING:
                    logger.warning(
                        "pool %s: replica %s exited unexpectedly (rc=%s)",
                        pool.name, replica.url, replica.process.returncode)
                    self._record_crash(pool)
                else:
                    logger.info("pool %s: drained replica %s exited",
                                pool.name, replica.url)
                replicas.remove(replica)
                changed = True

            for replica in replicas:
                if replica.state != STARTING:
                    continue
                payload = await self._probe_health(replica)
                if payload is not None and not payload.get("draining"):
                    replica.state = LIVE
                    # A healthy promotion proves the pool can boot:
                    # reset the backoff exponent (the breaker window
                    # drains on its own).
                    self._crash_streak[pool.name] = 0
                    self._next_spawn_ok[pool.name] = 0.0
                    changed = True

            for replica in replicas:
                if replica.state == DRAINING:
                    await self._escalate_drain(replica)

            # The rollout surge rides on top of the autoscaler's
            # desired count: the canary (and each roll step's
            # replacement) is an extra replica, so stable capacity
            # never dips mid-rollout.
            want = self.desired[pool.name] + self.rollout.surge(pool.name)
            active = [r for r in replicas if r.state != DRAINING]
            while len(active) < want:
                if not self._spawn_allowed(pool):
                    break
                if self._crash_streak[pool.name] > 0:
                    fleet_crash_respawns.labels(pool=pool.name).inc()
                    logger.info(
                        "pool %s: respawning after crash #%d (next "
                        "backoff %.2fs)", pool.name,
                        self._crash_streak[pool.name],
                        max(0.0, self._next_spawn_ok[pool.name]
                            - self._clock()))
                active.append(self._spawn(pool))
            # Scale down newest-first; a replica still starting never
            # served traffic, so stop those before draining live ones.
            # During a rollout, old-revision replicas are preferred
            # victims so a scale-down never eats the canary.
            excess = len(active) - want
            target_key = self.rollout.target_key(pool.name)
            for victim in sorted(
                    active,
                    key=lambda r: (target_key is not None
                                   and r.rev_key != target_key, r.port),
                    reverse=True)[:max(0, excess)]:
                if victim.state == STARTING:
                    victim.process.terminate()
                    victim.state = DRAINING  # reaped next pass
                    victim.drain_started = self._clock()
                    victim.sigterm_sent = self._clock()
                else:
                    await self._start_drain(victim)
                changed = True

        if changed:
            self._write_router_config()
        self._refresh_gauges()

    # ---- autoscale --------------------------------------------------------

    async def _scrape_signals(self) -> Dict[str, PoolSignals]:
        if not self.spec.router_url:
            return {}
        # Draining replicas are excluded: their last-scraped gauges go
        # stale, and counting them would inflate the pool's load right
        # when the autoscaler is trying to confirm the scale-down.
        url_to_pool = {
            replica.url: pool.name
            for pool in self.spec.pools
            for replica in self.replicas[pool.name]
            if replica.state != DRAINING}
        try:
            session = await self._http()
            url = self.spec.router_url.rstrip("/") + "/metrics"
            async with session.get(url) as resp:
                text = await resp.text()
        except Exception as e:
            logger.warning("cannot scrape router metrics: %s", e)
            return {}
        return signals_from_router_metrics(text, url_to_pool)

    async def autoscale_once(self) -> Dict[str, int]:
        """One autoscale tick; returns the desired counts per pool.

        Target tracking runs against the control variable (the current
        desired count), not the momentary live count — a replica that
        is still booting toward the target must not read as scale-down.
        """
        signals_by_pool = await self._scrape_signals()
        for pool in self.spec.pools:
            current = self.desired[pool.name]
            want = self.autoscalers[pool.name].desired(
                current, signals_by_pool.get(pool.name))
            if want != current:
                direction = "up" if want > current else "down"
                fleet_scale_events.labels(
                    pool=pool.name, direction=direction).inc()
                self.desired[pool.name] = want
        if self.pool_split is not None and signals_by_pool:
            adjusted = self.pool_split.rebalance(
                self.spec.pools, signals_by_pool, self.desired)
            for name, want in adjusted.items():
                if want != self.desired[name]:
                    direction = ("up" if want > self.desired[name]
                                 else "down")
                    fleet_scale_events.labels(
                        pool=name, direction=direction).inc()
                    self.desired[name] = want
        self._refresh_gauges()
        return dict(self.desired)

    # ---- loops ------------------------------------------------------------

    def request_stop(self) -> None:
        self._stopping = True

    async def run(self) -> None:
        """Reconcile every ``reconcile_interval_s``; autoscale every
        ``autoscale_interval_s`` (when ``router_url`` is set)."""
        import asyncio

        next_autoscale = self._clock()
        try:
            while not self._stopping:
                await self.reconcile_once()
                if (self.spec.router_url
                        and self._clock() >= next_autoscale):
                    await self.autoscale_once()
                    next_autoscale = (
                        self._clock() + self.spec.autoscale_interval_s)
                await asyncio.sleep(self.spec.reconcile_interval_s)
            await self.drain_all()
        finally:
            await self.close()

    async def drain_all(self) -> None:
        """Graceful teardown: drain every replica, wait for clean exits."""
        import asyncio

        for pool in self.spec.pools:
            self.desired[pool.name] = 0
        for pool in self.spec.pools:
            for replica in self.replicas[pool.name]:
                if replica.state != DRAINING:
                    await self._start_drain(replica)
        while any(r.process.poll() is None
                  for reps in self.replicas.values() for r in reps):
            for reps in self.replicas.values():
                for replica in reps:
                    if replica.process.poll() is None:
                        await self._escalate_drain(replica)
            await asyncio.sleep(0.1)
        await self.reconcile_once()
