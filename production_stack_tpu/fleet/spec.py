"""Declarative fleet specification.

A fleet spec is the CRD / values.yaml analog for bare-metal serving:
named pools of engine replicas, each with a role, replica bounds,
engine flags and autoscaler targets.  The reconciler
(:mod:`production_stack_tpu.fleet.manager`) owns making reality match
the spec; this module only parses and validates it.

Contract (enforced by the ``config-contract`` staticcheck rule, same
convention as EngineConfig): every dataclass field below must be
parsed from its JSON key in this file and documented in
docs/fleet.md, or listed in ``FLEET_INTERNAL_FIELDS`` — "operators
can't reach this knob" is always a decision, never an accident.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List

POOL_ROLES = ("prefill", "decode", "both")

# Fleet-spec fields that are deliberately not operator surface.
# Mirrors INTERNAL_FIELDS in engine/config.py; currently every field
# is reachable from the spec file.
FLEET_INTERNAL_FIELDS = ()

_NAME_RE = re.compile(r"^[a-z0-9][a-z0-9-]*$")


@dataclass
class AutoscalerSpec:
    """Target-tracking autoscaler knobs for one pool.

    A target of 0 disables that signal.  The desired replica count is
    ``ceil(current * ratio)`` where ratio is the worst (largest)
    observed/target ratio across enabled signals, clamped to the
    pool's replica bounds, with a hysteresis dead-band of
    ``tolerance`` around 1.0 and per-direction cooldowns.
    """

    enable: bool = True
    target_ttft_p99_s: float = 0.0
    target_itl_p99_s: float = 0.0
    target_waiting_per_replica: float = 0.0
    target_cache_usage: float = 0.0
    target_awaiting_kv: float = 0.0
    # SLO-ledger burn rate (docs/observability.md): the router's
    # fleet-wide vllm:slo_burn_rate{window="5m"} gauge as a scaling
    # hint — burn above target means the error budget is draining
    # faster than replicas can absorb. Fleet-wide, so it nudges every
    # pool that enables it.
    target_slo_burn_rate: float = 0.0
    tolerance: float = 0.1
    scale_up_cooldown_s: float = 15.0
    scale_down_cooldown_s: float = 60.0

    def __post_init__(self) -> None:
        for knob in ("target_ttft_p99_s", "target_itl_p99_s",
                     "target_waiting_per_replica", "target_cache_usage",
                     "target_awaiting_kv", "target_slo_burn_rate",
                     "scale_up_cooldown_s", "scale_down_cooldown_s"):
            if getattr(self, knob) < 0:
                raise ValueError(f"autoscaler.{knob} must be >= 0")
        if not 0.0 <= self.tolerance < 1.0:
            raise ValueError("autoscaler.tolerance must be in [0, 1)")

    @classmethod
    def from_dict(cls, raw: Dict[str, Any]) -> "AutoscalerSpec":
        return cls(
            enable=bool(raw.get("enable", True)),
            target_ttft_p99_s=float(raw.get("target_ttft_p99_s", 0.0)),
            target_itl_p99_s=float(raw.get("target_itl_p99_s", 0.0)),
            target_waiting_per_replica=float(
                raw.get("target_waiting_per_replica", 0.0)),
            target_cache_usage=float(raw.get("target_cache_usage", 0.0)),
            target_awaiting_kv=float(raw.get("target_awaiting_kv", 0.0)),
            target_slo_burn_rate=float(
                raw.get("target_slo_burn_rate", 0.0)),
            tolerance=float(raw.get("tolerance", 0.1)),
            scale_up_cooldown_s=float(raw.get("scale_up_cooldown_s", 15.0)),
            scale_down_cooldown_s=float(
                raw.get("scale_down_cooldown_s", 60.0)),
        )


@dataclass
class RevisionSpec:
    """What version of the engine a pool's replicas should run.

    Changing a pool's revision in the spec is the rollout trigger
    (docs/fleet.md): the reconciler does not restart anything in
    place; the :class:`~production_stack_tpu.fleet.rollout.RolloutController`
    walks the pool from the old revision to this one behind a scored
    canary.  Two revisions are the same iff both ``build_id`` and
    ``engine_flags`` match.
    """

    # Opaque build identifier (image tag, git sha).  Passed to the
    # engine as ``--build-id`` and surfaced in its /version and
    # /health payloads so revision membership is verifiable.
    build_id: str = ""
    # Extra engine flags for this revision, appended after the pool's
    # own engine_flags (so a revision can override them).
    engine_flags: List[str] = field(default_factory=list)

    def key(self) -> tuple:
        return (self.build_id, tuple(self.engine_flags))

    @classmethod
    def from_dict(cls, raw: Dict[str, Any]) -> "RevisionSpec":
        return cls(
            build_id=str(raw.get("build_id", "")),
            engine_flags=[str(f) for f in raw.get("engine_flags", [])],
        )


ROLLOUT_DRAIN_MODES = ("migrate", "wait")


@dataclass
class RolloutSpec:
    """Canary judge + rollout pacing knobs for one pool.

    A threshold of 0 disables that signal.  The judge scores the
    canary once at the end of the bake window; any failing signal
    triggers automatic rollback (docs/fleet.md).
    """

    enable: bool = True
    # Fraction of the pool's dispatch traffic steered at the canary
    # while it bakes (the rest goes to the stable set).
    canary_weight: float = 0.1
    # How long the canary takes weighted traffic before it is judged.
    bake_s: float = 300.0
    # Judge: fail when the fleet 5m SLO burn rate exceeds this.
    max_slo_burn_rate_5m: float = 1.0
    # Judge: fail when any perf-drift sentinel phase is tripped.
    fail_on_perf_drift: bool = True
    # Judge: fail when the canary crashed at least this many times
    # during the bake (it is respawned at the same revision meanwhile).
    max_crash_streak: int = 1
    # Judge: fail when the router charged the canary with more than
    # this many breaker failures (vllm:server_errors_total delta).
    max_server_errors: float = 0.0
    # Judge: fail when the canary's p99 TTFT or ITL exceeds the worst
    # stable replica's by more than this factor.
    max_latency_ratio: float = 0.0
    # How old replicas are drained during the roll: "migrate"
    # proactively resumes their checkpointed streams on new-revision
    # replicas via POST /v1/resume (zero-loss even for multi-minute
    # streams, docs/crash_recovery.md); "wait" lets in-flight work
    # finish naturally before the replica exits.
    drain_mode: str = "migrate"

    def __post_init__(self) -> None:
        if not 0.0 < self.canary_weight <= 1.0:
            raise ValueError(
                "rollout.canary_weight must be in (0, 1]")
        for knob in ("bake_s", "max_slo_burn_rate_5m",
                     "max_server_errors", "max_latency_ratio"):
            if getattr(self, knob) < 0:
                raise ValueError(f"rollout.{knob} must be >= 0")
        if self.max_crash_streak < 0:
            raise ValueError("rollout.max_crash_streak must be >= 0")
        if self.drain_mode not in ROLLOUT_DRAIN_MODES:
            raise ValueError(
                f"rollout.drain_mode {self.drain_mode!r} not in "
                f"{ROLLOUT_DRAIN_MODES}")

    @classmethod
    def from_dict(cls, raw: Dict[str, Any]) -> "RolloutSpec":
        return cls(
            enable=bool(raw.get("enable", True)),
            canary_weight=float(raw.get("canary_weight", 0.1)),
            bake_s=float(raw.get("bake_s", 300.0)),
            max_slo_burn_rate_5m=float(
                raw.get("max_slo_burn_rate_5m", 1.0)),
            fail_on_perf_drift=bool(raw.get("fail_on_perf_drift", True)),
            max_crash_streak=int(raw.get("max_crash_streak", 1)),
            max_server_errors=float(raw.get("max_server_errors", 0.0)),
            max_latency_ratio=float(raw.get("max_latency_ratio", 0.0)),
            drain_mode=str(raw.get("drain_mode", "migrate")),
        )


@dataclass
class PoolSpec:
    """One named pool of interchangeable engine replicas."""

    name: str
    role: str = "both"
    min_replicas: int = 1
    max_replicas: int = 1
    model: str = "fake"
    engine_flags: List[str] = field(default_factory=list)
    # Optional argv template overriding the default engine-server
    # command; each element is ``str.format``-ed with {port}, {model}
    # and {role}.  Tests use this to run pools of fake engines.
    command: List[str] = field(default_factory=list)
    autoscaler: AutoscalerSpec = field(default_factory=AutoscalerSpec)
    # Target engine revision; changing it in the spec drives a
    # canary-scored surge rolling update (docs/fleet.md).
    revision: RevisionSpec = field(default_factory=RevisionSpec)
    rollout: RolloutSpec = field(default_factory=RolloutSpec)
    # Crash-loop containment (docs/crash_recovery.md): replicas that
    # exit without a drain are respawned with jittered exponential
    # backoff, and a pool seeing ``crash_loop_threshold`` crashes
    # within ``crash_loop_window_s`` stops respawning until the window
    # cools — a broken image must not melt the host with a fork storm.
    respawn_backoff_base_s: float = 1.0
    respawn_backoff_max_s: float = 30.0
    crash_loop_threshold: int = 5
    crash_loop_window_s: float = 60.0

    def __post_init__(self) -> None:
        if not _NAME_RE.match(self.name or ""):
            raise ValueError(
                f"pool name {self.name!r} must match {_NAME_RE.pattern}")
        if self.role not in POOL_ROLES:
            raise ValueError(
                f"pool {self.name}: role {self.role!r} not in {POOL_ROLES}")
        if self.min_replicas < 0:
            raise ValueError(f"pool {self.name}: min_replicas must be >= 0")
        if self.max_replicas < max(1, self.min_replicas):
            raise ValueError(
                f"pool {self.name}: max_replicas must be >= "
                "max(1, min_replicas)")
        if self.respawn_backoff_base_s < 0:
            raise ValueError(
                f"pool {self.name}: respawn_backoff_base_s must be >= 0")
        if self.respawn_backoff_max_s < self.respawn_backoff_base_s:
            raise ValueError(
                f"pool {self.name}: respawn_backoff_max_s must be >= "
                "respawn_backoff_base_s")
        if self.crash_loop_threshold < 0:
            raise ValueError(
                f"pool {self.name}: crash_loop_threshold must be >= 0 "
                "(0 disables the breaker)")
        if self.crash_loop_window_s <= 0:
            raise ValueError(
                f"pool {self.name}: crash_loop_window_s must be > 0")

    @classmethod
    def from_dict(cls, raw: Dict[str, Any]) -> "PoolSpec":
        return cls(
            name=raw.get("name", ""),
            role=raw.get("role", "both"),
            min_replicas=int(raw.get("min_replicas", 1)),
            max_replicas=int(raw.get("max_replicas", 1)),
            model=raw.get("model", "fake"),
            engine_flags=[str(f) for f in raw.get("engine_flags", [])],
            command=[str(c) for c in raw.get("command", [])],
            autoscaler=AutoscalerSpec.from_dict(raw.get("autoscaler", {})),
            revision=RevisionSpec.from_dict(raw.get("revision", {})),
            rollout=RolloutSpec.from_dict(raw.get("rollout", {})),
            respawn_backoff_base_s=float(
                raw.get("respawn_backoff_base_s", 1.0)),
            respawn_backoff_max_s=float(
                raw.get("respawn_backoff_max_s", 30.0)),
            crash_loop_threshold=int(raw.get("crash_loop_threshold", 5)),
            crash_loop_window_s=float(
                raw.get("crash_loop_window_s", 60.0)),
        )


@dataclass
class FleetSpec:
    """The whole fleet: pools plus shared wiring."""

    pools: List[PoolSpec] = field(default_factory=list)
    # Replica ports are allocated from [port_start, port_end].
    port_start: int = 8100
    port_end: int = 8199
    # Router /metrics base URL the autoscaler scrapes; empty disables
    # autoscaling (desired counts stay at min_replicas / manual).
    router_url: str = ""
    # Dynamic-config JSON the router watches; the reconciler rewrites
    # it on every membership change (registration/deregistration).
    router_config_path: str = ""
    routing_logic: str = "roundrobin"
    # How long a draining replica may take to finish in-flight work
    # before the reconciler escalates to SIGTERM (never SIGKILL while
    # sequences are running).  0 waits forever.
    drain_timeout_s: float = 120.0
    reconcile_interval_s: float = 1.0
    autoscale_interval_s: float = 5.0
    # JSON control file the rollout controller polls for operator
    # commands; ``python -m production_stack_tpu.fleet --rollout-cmd
    # pause|resume|abort`` writes it (docs/fleet.md).  Empty disables
    # the operator channel.
    rollout_control_path: str = ""
    # Self-tuning pool split (docs/autotuning.md): bias the
    # prefill-vs-decode replica split from the phase-time signals the
    # autoscaler already scrapes. Off by default; only meaningful with
    # one prefill-role and one decode-role pool.
    autotune_pool_split: bool = False

    def __post_init__(self) -> None:
        if not self.pools:
            raise ValueError("fleet spec needs at least one pool")
        names = [p.name for p in self.pools]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate pool names in {names}")
        if not 0 < self.port_start <= self.port_end <= 65535:
            raise ValueError(
                f"bad port range [{self.port_start}, {self.port_end}]")
        capacity = self.port_end - self.port_start + 1
        ceiling = sum(p.max_replicas for p in self.pools)
        if ceiling > capacity:
            raise ValueError(
                f"port range holds {capacity} replicas but pools allow "
                f"up to {ceiling}")
        if self.drain_timeout_s < 0:
            raise ValueError("drain_timeout_s must be >= 0")
        if self.reconcile_interval_s <= 0 or self.autoscale_interval_s <= 0:
            raise ValueError("reconcile/autoscale intervals must be > 0")

    @classmethod
    def from_dict(cls, raw: Dict[str, Any]) -> "FleetSpec":
        return cls(
            pools=[PoolSpec.from_dict(p) for p in raw.get("pools", [])],
            port_start=int(raw.get("port_start", 8100)),
            port_end=int(raw.get("port_end", 8199)),
            router_url=raw.get("router_url", ""),
            router_config_path=raw.get("router_config_path", ""),
            routing_logic=raw.get("routing_logic", "roundrobin"),
            drain_timeout_s=float(raw.get("drain_timeout_s", 120.0)),
            reconcile_interval_s=float(raw.get("reconcile_interval_s", 1.0)),
            autoscale_interval_s=float(raw.get("autoscale_interval_s", 5.0)),
            rollout_control_path=raw.get("rollout_control_path", ""),
            autotune_pool_split=bool(
                raw.get("autotune_pool_split", False)),
        )

    @classmethod
    def from_json(cls, text: str) -> "FleetSpec":
        raw = json.loads(text)
        if not isinstance(raw, dict):
            raise ValueError("fleet spec must be a JSON object")
        return cls.from_dict(raw)


def load_fleet_spec(path: str) -> FleetSpec:
    with open(path) as f:
        return FleetSpec.from_json(f.read())
