"""Declarative fleet specification.

A fleet spec is the CRD / values.yaml analog for bare-metal serving:
named pools of engine replicas, each with a role, replica bounds,
engine flags and autoscaler targets.  The reconciler
(:mod:`production_stack_tpu.fleet.manager`) owns making reality match
the spec; this module only parses and validates it.

Contract (enforced by the ``config-contract`` staticcheck rule, same
convention as EngineConfig): every dataclass field below must be
parsed from its JSON key in this file and documented in
docs/fleet.md, or listed in ``FLEET_INTERNAL_FIELDS`` — "operators
can't reach this knob" is always a decision, never an accident.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List

POOL_ROLES = ("prefill", "decode", "both")

# Fleet-spec fields that are deliberately not operator surface.
# Mirrors INTERNAL_FIELDS in engine/config.py; currently every field
# is reachable from the spec file.
FLEET_INTERNAL_FIELDS = ()

_NAME_RE = re.compile(r"^[a-z0-9][a-z0-9-]*$")


@dataclass
class AutoscalerSpec:
    """Target-tracking autoscaler knobs for one pool.

    A target of 0 disables that signal.  The desired replica count is
    ``ceil(current * ratio)`` where ratio is the worst (largest)
    observed/target ratio across enabled signals, clamped to the
    pool's replica bounds, with a hysteresis dead-band of
    ``tolerance`` around 1.0 and per-direction cooldowns.
    """

    enable: bool = True
    target_ttft_p99_s: float = 0.0
    target_itl_p99_s: float = 0.0
    target_waiting_per_replica: float = 0.0
    target_cache_usage: float = 0.0
    target_awaiting_kv: float = 0.0
    # SLO-ledger burn rate (docs/observability.md): the router's
    # fleet-wide vllm:slo_burn_rate{window="5m"} gauge as a scaling
    # hint — burn above target means the error budget is draining
    # faster than replicas can absorb. Fleet-wide, so it nudges every
    # pool that enables it.
    target_slo_burn_rate: float = 0.0
    tolerance: float = 0.1
    scale_up_cooldown_s: float = 15.0
    scale_down_cooldown_s: float = 60.0

    def __post_init__(self) -> None:
        for knob in ("target_ttft_p99_s", "target_itl_p99_s",
                     "target_waiting_per_replica", "target_cache_usage",
                     "target_awaiting_kv", "target_slo_burn_rate",
                     "scale_up_cooldown_s", "scale_down_cooldown_s"):
            if getattr(self, knob) < 0:
                raise ValueError(f"autoscaler.{knob} must be >= 0")
        if not 0.0 <= self.tolerance < 1.0:
            raise ValueError("autoscaler.tolerance must be in [0, 1)")

    @classmethod
    def from_dict(cls, raw: Dict[str, Any]) -> "AutoscalerSpec":
        return cls(
            enable=bool(raw.get("enable", True)),
            target_ttft_p99_s=float(raw.get("target_ttft_p99_s", 0.0)),
            target_itl_p99_s=float(raw.get("target_itl_p99_s", 0.0)),
            target_waiting_per_replica=float(
                raw.get("target_waiting_per_replica", 0.0)),
            target_cache_usage=float(raw.get("target_cache_usage", 0.0)),
            target_awaiting_kv=float(raw.get("target_awaiting_kv", 0.0)),
            target_slo_burn_rate=float(
                raw.get("target_slo_burn_rate", 0.0)),
            tolerance=float(raw.get("tolerance", 0.1)),
            scale_up_cooldown_s=float(raw.get("scale_up_cooldown_s", 15.0)),
            scale_down_cooldown_s=float(
                raw.get("scale_down_cooldown_s", 60.0)),
        )


@dataclass
class PoolSpec:
    """One named pool of interchangeable engine replicas."""

    name: str
    role: str = "both"
    min_replicas: int = 1
    max_replicas: int = 1
    model: str = "fake"
    engine_flags: List[str] = field(default_factory=list)
    # Optional argv template overriding the default engine-server
    # command; each element is ``str.format``-ed with {port}, {model}
    # and {role}.  Tests use this to run pools of fake engines.
    command: List[str] = field(default_factory=list)
    autoscaler: AutoscalerSpec = field(default_factory=AutoscalerSpec)
    # Crash-loop containment (docs/crash_recovery.md): replicas that
    # exit without a drain are respawned with jittered exponential
    # backoff, and a pool seeing ``crash_loop_threshold`` crashes
    # within ``crash_loop_window_s`` stops respawning until the window
    # cools — a broken image must not melt the host with a fork storm.
    respawn_backoff_base_s: float = 1.0
    respawn_backoff_max_s: float = 30.0
    crash_loop_threshold: int = 5
    crash_loop_window_s: float = 60.0

    def __post_init__(self) -> None:
        if not _NAME_RE.match(self.name or ""):
            raise ValueError(
                f"pool name {self.name!r} must match {_NAME_RE.pattern}")
        if self.role not in POOL_ROLES:
            raise ValueError(
                f"pool {self.name}: role {self.role!r} not in {POOL_ROLES}")
        if self.min_replicas < 0:
            raise ValueError(f"pool {self.name}: min_replicas must be >= 0")
        if self.max_replicas < max(1, self.min_replicas):
            raise ValueError(
                f"pool {self.name}: max_replicas must be >= "
                "max(1, min_replicas)")
        if self.respawn_backoff_base_s < 0:
            raise ValueError(
                f"pool {self.name}: respawn_backoff_base_s must be >= 0")
        if self.respawn_backoff_max_s < self.respawn_backoff_base_s:
            raise ValueError(
                f"pool {self.name}: respawn_backoff_max_s must be >= "
                "respawn_backoff_base_s")
        if self.crash_loop_threshold < 0:
            raise ValueError(
                f"pool {self.name}: crash_loop_threshold must be >= 0 "
                "(0 disables the breaker)")
        if self.crash_loop_window_s <= 0:
            raise ValueError(
                f"pool {self.name}: crash_loop_window_s must be > 0")

    @classmethod
    def from_dict(cls, raw: Dict[str, Any]) -> "PoolSpec":
        return cls(
            name=raw.get("name", ""),
            role=raw.get("role", "both"),
            min_replicas=int(raw.get("min_replicas", 1)),
            max_replicas=int(raw.get("max_replicas", 1)),
            model=raw.get("model", "fake"),
            engine_flags=[str(f) for f in raw.get("engine_flags", [])],
            command=[str(c) for c in raw.get("command", [])],
            autoscaler=AutoscalerSpec.from_dict(raw.get("autoscaler", {})),
            respawn_backoff_base_s=float(
                raw.get("respawn_backoff_base_s", 1.0)),
            respawn_backoff_max_s=float(
                raw.get("respawn_backoff_max_s", 30.0)),
            crash_loop_threshold=int(raw.get("crash_loop_threshold", 5)),
            crash_loop_window_s=float(
                raw.get("crash_loop_window_s", 60.0)),
        )


@dataclass
class FleetSpec:
    """The whole fleet: pools plus shared wiring."""

    pools: List[PoolSpec] = field(default_factory=list)
    # Replica ports are allocated from [port_start, port_end].
    port_start: int = 8100
    port_end: int = 8199
    # Router /metrics base URL the autoscaler scrapes; empty disables
    # autoscaling (desired counts stay at min_replicas / manual).
    router_url: str = ""
    # Dynamic-config JSON the router watches; the reconciler rewrites
    # it on every membership change (registration/deregistration).
    router_config_path: str = ""
    routing_logic: str = "roundrobin"
    # How long a draining replica may take to finish in-flight work
    # before the reconciler escalates to SIGTERM (never SIGKILL while
    # sequences are running).  0 waits forever.
    drain_timeout_s: float = 120.0
    reconcile_interval_s: float = 1.0
    autoscale_interval_s: float = 5.0

    def __post_init__(self) -> None:
        if not self.pools:
            raise ValueError("fleet spec needs at least one pool")
        names = [p.name for p in self.pools]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate pool names in {names}")
        if not 0 < self.port_start <= self.port_end <= 65535:
            raise ValueError(
                f"bad port range [{self.port_start}, {self.port_end}]")
        capacity = self.port_end - self.port_start + 1
        ceiling = sum(p.max_replicas for p in self.pools)
        if ceiling > capacity:
            raise ValueError(
                f"port range holds {capacity} replicas but pools allow "
                f"up to {ceiling}")
        if self.drain_timeout_s < 0:
            raise ValueError("drain_timeout_s must be >= 0")
        if self.reconcile_interval_s <= 0 or self.autoscale_interval_s <= 0:
            raise ValueError("reconcile/autoscale intervals must be > 0")

    @classmethod
    def from_dict(cls, raw: Dict[str, Any]) -> "FleetSpec":
        return cls(
            pools=[PoolSpec.from_dict(p) for p in raw.get("pools", [])],
            port_start=int(raw.get("port_start", 8100)),
            port_end=int(raw.get("port_end", 8199)),
            router_url=raw.get("router_url", ""),
            router_config_path=raw.get("router_config_path", ""),
            routing_logic=raw.get("routing_logic", "roundrobin"),
            drain_timeout_s=float(raw.get("drain_timeout_s", 120.0)),
            reconcile_interval_s=float(raw.get("reconcile_interval_s", 1.0)),
            autoscale_interval_s=float(raw.get("autoscale_interval_s", 5.0)),
        )

    @classmethod
    def from_json(cls, text: str) -> "FleetSpec":
        raw = json.loads(text)
        if not isinstance(raw, dict):
            raise ValueError("fleet spec must be a JSON object")
        return cls.from_dict(raw)


def load_fleet_spec(path: str) -> FleetSpec:
    with open(path) as f:
        return FleetSpec.from_json(f.read())
