"""SLO-driven fleet manager: declarative engine pools, a reconciler
that owns engine lifecycle, and zero-loss drain.

The C++ control-plane agent (``controlplane/``) renders configuration
for engines that something else runs; this package is that something
else for bare-metal / single-host deployments: it spawns engine
server processes from a declarative :class:`FleetSpec`, registers them
with the router through the dynamic-config hot-reload file, scales
pools against router-exported SLO metrics, and drains replicas to
zero in-flight before ever stopping a process.  See docs/fleet.md.
"""

from production_stack_tpu.fleet.spec import (  # noqa: F401
    AutoscalerSpec,
    FleetSpec,
    PoolSpec,
    load_fleet_spec,
)
from production_stack_tpu.fleet.autoscaler import (  # noqa: F401
    PoolAutoscaler,
    PoolSignals,
    signals_from_router_metrics,
)
from production_stack_tpu.fleet.manager import (  # noqa: F401
    FleetManager,
    Replica,
)
