"""Fleet manager CLI: ``python -m production_stack_tpu.fleet``.

Loads a fleet spec, then runs the reconcile + autoscale loops until
interrupted; Ctrl-C drains every replica to zero in-flight before
exiting.  Flags override the matching spec fields so one spec file
can serve several environments (see docs/fleet.md).
"""

from __future__ import annotations

import argparse
import asyncio
import signal

from production_stack_tpu.fleet.manager import FleetManager
from production_stack_tpu.fleet.spec import load_fleet_spec
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        prog="python -m production_stack_tpu.fleet",
        description="SLO-driven engine fleet manager")
    parser.add_argument("--spec", required=True,
                        help="Path to the fleet spec JSON (docs/fleet.md)")
    parser.add_argument("--router-url", default=None,
                        help="Override the spec's router_url (autoscaler "
                             "metrics source)")
    parser.add_argument("--router-config-path", default=None,
                        help="Override the spec's router_config_path "
                             "(dynamic-config JSON the router watches)")
    parser.add_argument("--reconcile-interval-s", type=float, default=None,
                        help="Override the spec's reconcile_interval_s")
    parser.add_argument("--autoscale-interval-s", type=float, default=None,
                        help="Override the spec's autoscale_interval_s")
    parser.add_argument("--drain-timeout-s", type=float, default=None,
                        help="Override the spec's drain_timeout_s")
    return parser.parse_args(argv)


async def _amain(args: argparse.Namespace) -> None:
    spec = load_fleet_spec(args.spec)
    if args.router_url is not None:
        spec.router_url = args.router_url
    if args.router_config_path is not None:
        spec.router_config_path = args.router_config_path
    if args.reconcile_interval_s is not None:
        spec.reconcile_interval_s = args.reconcile_interval_s
    if args.autoscale_interval_s is not None:
        spec.autoscale_interval_s = args.autoscale_interval_s
    if args.drain_timeout_s is not None:
        spec.drain_timeout_s = args.drain_timeout_s

    manager = FleetManager(spec)
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, manager.request_stop)
    logger.info("Fleet manager running: %d pool(s), ports [%d, %d]",
                len(spec.pools), spec.port_start, spec.port_end)
    await manager.run()


def main(argv=None) -> None:
    asyncio.run(_amain(parse_args(argv)))


if __name__ == "__main__":
    main()
