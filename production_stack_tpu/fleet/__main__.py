"""Fleet manager CLI: ``python -m production_stack_tpu.fleet``.

Loads a fleet spec, then runs the reconcile + autoscale loops until
interrupted; Ctrl-C drains every replica to zero in-flight before
exiting.  Flags override the matching spec fields so one spec file
can serve several environments (see docs/fleet.md).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import signal
import sys
import time

from production_stack_tpu.fleet.manager import FleetManager
from production_stack_tpu.fleet.spec import load_fleet_spec
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        prog="python -m production_stack_tpu.fleet",
        description="SLO-driven engine fleet manager")
    parser.add_argument("--spec", required=True,
                        help="Path to the fleet spec JSON (docs/fleet.md)")
    parser.add_argument("--router-url", default=None,
                        help="Override the spec's router_url (autoscaler "
                             "metrics source)")
    parser.add_argument("--router-config-path", default=None,
                        help="Override the spec's router_config_path "
                             "(dynamic-config JSON the router watches)")
    parser.add_argument("--reconcile-interval-s", type=float, default=None,
                        help="Override the spec's reconcile_interval_s")
    parser.add_argument("--autoscale-interval-s", type=float, default=None,
                        help="Override the spec's autoscale_interval_s")
    parser.add_argument("--drain-timeout-s", type=float, default=None,
                        help="Override the spec's drain_timeout_s")
    parser.add_argument("--rollout-cmd", default=None,
                        choices=("pause", "resume", "abort"),
                        help="Instead of running the manager, write a "
                             "rollout control command to the spec's "
                             "rollout_control_path and exit — the "
                             "running manager's rollout controller "
                             "picks it up on its next reconcile tick "
                             "(docs/fleet.md)")
    parser.add_argument("--rollout-pool", default=None,
                        help="Restrict --rollout-cmd to one pool "
                             "(default: every pool with an active "
                             "rollout)")
    return parser.parse_args(argv)


def send_rollout_command(spec, cmd: str, pool=None) -> str:
    """Writes the operator command file the RolloutController polls.
    A strictly increasing ``ts`` dedupes: the controller only applies
    commands newer than the last one it saw."""
    path = spec.rollout_control_path
    if not path:
        raise SystemExit(
            "spec has no rollout_control_path; set it to use "
            "--rollout-cmd (docs/fleet.md)")
    payload = {"ts": time.time(), "cmd": cmd}
    if pool:
        payload["pool"] = pool
    with open(path, "w") as f:
        json.dump(payload, f)
    return path


async def _amain(args: argparse.Namespace) -> None:
    spec = load_fleet_spec(args.spec)
    if args.router_url is not None:
        spec.router_url = args.router_url
    if args.router_config_path is not None:
        spec.router_config_path = args.router_config_path
    if args.reconcile_interval_s is not None:
        spec.reconcile_interval_s = args.reconcile_interval_s
    if args.autoscale_interval_s is not None:
        spec.autoscale_interval_s = args.autoscale_interval_s
    if args.drain_timeout_s is not None:
        spec.drain_timeout_s = args.drain_timeout_s

    manager = FleetManager(spec)
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, manager.request_stop)
    logger.info("Fleet manager running: %d pool(s), ports [%d, %d]",
                len(spec.pools), spec.port_start, spec.port_end)
    await manager.run()


def main(argv=None) -> None:
    args = parse_args(argv)
    if args.rollout_cmd is not None:
        spec = load_fleet_spec(args.spec)
        path = send_rollout_command(spec, args.rollout_cmd,
                                    pool=args.rollout_pool)
        print(f"rollout {args.rollout_cmd} -> {path}", file=sys.stderr)
        return
    asyncio.run(_amain(args))


if __name__ == "__main__":
    main()
