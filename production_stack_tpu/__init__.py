"""production-stack-tpu: a TPU-native LLM serving stack.

A ground-up rebuild of the vLLM Production Stack's capability surface
(reference: pouyahmdn/production-stack) designed TPU-first:

- ``router``    — OpenAI-compatible request router (aiohttp data plane),
                  service discovery, pluggable routing logic, stats,
                  Prometheus metrics, dynamic config hot-reload.
- ``engine``    — the piece the reference outsources to vLLM: a JAX/XLA
                  serving engine with paged KV cache, continuous batching,
                  and Pallas attention kernels, exposing the same
                  OpenAI-compatible API + vLLM-compatible /metrics names.
- ``models``    — JAX model definitions (Llama, OPT, ...).
- ``ops``       — Pallas kernels + XLA reference implementations.
- ``parallel``  — mesh/sharding utilities (tensor parallel over ICI,
                  multi-host via jax.distributed).
"""

from production_stack_tpu.version import __version__

__all__ = ["__version__"]
