"""Request/sequence state for the continuous-batching engine."""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class SamplingParams:
    max_tokens: int = 128
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0  # 0 = disabled
    stop_token_ids: List[int] = field(default_factory=list)
    # Text-level stop sequences (OpenAI ``stop``): enforced by the
    # server on the detokenized stream (engine/server.py
    # _StopStringScanner) — token-level state can't see them because
    # a stop string may span token boundaries.
    stop_strings: List[str] = field(default_factory=list)
    # OpenAI penalties over the tokens GENERATED so far (presence:
    # flat once seen; frequency: per occurrence) and vLLM/HF-style
    # repetition penalty over prompt+output. Applied on device inside
    # the compiled step (ops/sampling.py apply_penalties).
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0
    ignore_eos: bool = False
    seed: Optional[int] = None
    # OpenAI ``logprobs``/``top_logprobs``: return the sampled token's
    # logprob and up to top_logprobs alternatives per position
    # (computed on device from the unmodified distribution; capped at
    # the compiled width, engine/model_runner.py TOP_LOGPROBS_WIDTH).
    logprobs: bool = False
    top_logprobs: int = 0
    # OpenAI ``logit_bias``: {token_id: bias in [-100, 100]} added to
    # the logits before sampling (after penalties; logprobs report the
    # raw distribution per the OpenAI contract). Applied on device as
    # a dense [B, vocab] add only when some row in the batch uses it
    # (model_runner._bias_payload).
    logit_bias: Optional[Dict[int, float]] = None
    # vLLM ``min_tokens``: EOS and stop_token_ids cannot be GENERATED
    # until this many tokens have been emitted — their logits are
    # suppressed on device while under the minimum
    # (model_runner._suppress_payload), matching vLLM's semantics
    # (text-level stop strings are not gated, as in vLLM).
    min_tokens: int = 0
    # OpenAI ``response_format``: "json" = guided JSON decoding via
    # the byte-level automaton (engine/guided.py); the device masks
    # inadmissible tokens inside the sampling step. None = free text.
    guided: Optional[str] = None

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0

    @property
    def needs_penalties(self) -> bool:
        return (self.presence_penalty != 0.0
                or self.frequency_penalty != 0.0
                or self.repetition_penalty != 1.0)


class SequenceState(enum.Enum):
    WAITING = "waiting"  # queued, prompt not (fully) prefilled
    # Disaggregated handoff admission (docs/disaggregation.md): the
    # sequence arrived via a prefill->decode handoff and is parked
    # until its KV pages are reachable in an offload tier (or the
    # handoff timeout elapses and it degrades to recompute). Counted
    # in num_requests_waiting; skipped by prefill planning.
    AWAITING_KV = "awaiting_kv"
    RUNNING = "running"  # decoding
    FINISHED = "finished"
    ABORTED = "aborted"


# The sequence lifecycle, as data. Single source of truth for every
# ``seq.state`` change in the stack: ``Sequence.transition`` validates
# against it at runtime, the ``state-machine`` staticcheck rule flags
# direct ``.state =`` writes and untabled transitions at lint time,
# and docs/sequence_states.md renders it (kept in sync both
# directions by the same rule). ``"new"`` is a pseudo-state meaning
# "constructed with this initial state".
SEQUENCE_TRANSITIONS = (
    ("new", "waiting",
     "ordinary admission: request queued for prefill"),
    ("new", "awaiting_kv",
     "disagg handoff / crash resume arrives parked until its shipped "
     "KV is reachable in an offload tier"),
    ("waiting", "running",
     "last prefill chunk executed and the first token sampled"),
    ("waiting", "awaiting_kv",
     "cold-start probe: park a fresh request to ask the shared KV "
     "tier for its prefix before computing"),
    ("waiting", "aborted",
     "admission rejected (queue full, oversized prompt) or client "
     "abort while queued"),
    ("awaiting_kv", "waiting",
     "parked KV became reachable (admit for restore) or the wait "
     "degraded to recompute (timeout / miss / no tier)"),
    ("awaiting_kv", "aborted",
     "client abort or engine shutdown while parked"),
    ("running", "waiting",
     "preempted for KV-cache pressure; generated tokens folded into "
     "the prompt for recompute"),
    ("running", "awaiting_kv",
     "preempt-to-offload: pages shipped to the offload tier, parked "
     "for re-admission"),
    ("running", "finished",
     "stop token / length budget / disagg handoff retirement"),
    ("running", "aborted",
     "client abort or crash containment mid-decode"),
)

_ALLOWED_TRANSITIONS = frozenset(
    (src, dst) for src, dst, _ in SEQUENCE_TRANSITIONS)

SEQUENCE_INITIAL_STATES = frozenset(
    dst for src, dst, _ in SEQUENCE_TRANSITIONS if src == "new")


class FinishReason(str, enum.Enum):
    STOP = "stop"
    LENGTH = "length"
    ABORT = "abort"
    # Disaggregated prefill role: the engine computed the prompt KV,
    # shipped it to the offload tier and retired the sequence after
    # the first sampled token; decoding continues on a decode-role
    # engine (docs/disaggregation.md).
    HANDOFF = "handoff"


@dataclass
class Sequence:
    seq_id: str
    prompt_token_ids: List[int]
    sampling: SamplingParams
    arrival_time: float = field(default_factory=time.time)

    state: SequenceState = SequenceState.WAITING
    output_token_ids: List[int] = field(default_factory=list)
    # How many prompt tokens have been prefilled (incl. prefix-cache hits).
    num_computed_tokens: int = 0
    pages: List[int] = field(default_factory=list)
    num_hashed_pages: int = 0
    finish_reason: Optional[FinishReason] = None
    first_token_time: Optional[float] = None
    # When the scheduler first planned this sequence's prefill: splits
    # client TTFT into queueing (arrival -> here) vs prefill compute
    # (here -> first_token_time) — VERDICT r2 asked for the honest
    # decomposition.
    first_scheduled_time: Optional[float] = None
    # Wall time of the latest decode-step emission for this sequence:
    # inter-token latency is observed per token as steps complete
    # (engine/metrics.py on_decode_tokens), so multi-token speculative
    # steps are accounted at their true per-token cadence.
    last_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    # LoRA adapter slot (0 = base model; see engine/lora.py).
    lora_id: int = 0
    # Prefix-cache namespace root (kv_cache.chain_hashes): nonzero for
    # adapter requests so adapter-specific KV never cross-hits.
    cache_salt: int = 0
    # Server-side stream hook (asyncio queue or callable), opaque here.
    output_sink: Any = None
    # Guided-decoding automaton state (engine/guided.py); None for
    # unconstrained rows. Host-side mirror of the device carry.
    fsm_state: Optional[int] = None
    # Generated tokens folded back into the prompt by preemption
    # (scheduler._preempt): every "tokens generated so far" budget
    # (max_tokens, min_tokens, seeded-sampling emitted index) must
    # count these or a preempted sequence restarts its windows.
    num_prior_output_tokens: int = 0
    # Disaggregated serving (docs/disaggregation.md): a prefill-role
    # request finishes after the first sampled token — the engine
    # ships the committed KV pages to the offload tier and returns a
    # handoff descriptor instead of decoding.
    handoff_prefill: bool = False
    # Decode-side handoff bookkeeping: when the sequence was parked in
    # AWAITING_KV (admission latency = admit time - this).
    handoff_arrival_time: Optional[float] = None
    # End-to-end trace id (docs/observability.md): the router's
    # x-request-id, carried so engine spans on every hop of a
    # disaggregated request stitch to the same router span.
    request_id: Optional[str] = None
    # QoS priority class (docs/qos.md): int value of qos.Priority —
    # lower is more important. Admission sorts waiting sequences by
    # (priority, arrival_time); preemption picks the max of the same
    # tuple (lowest-priority, newest victim). Plain int so this module
    # stays import-light.
    priority: int = 1
    # QoS degradation ladder: the router marks throttled-tenant
    # requests spec-off; the scheduler then never spends speculative
    # draft/verify slack on them (docs/qos.md).
    spec_off: bool = False
    # Self-tuning telemetry + knob (docs/autotuning.md): lifetime
    # draft/accept counters the spec-k controller windows per tick,
    # and its per-sequence draft-length cap. The cap rides the same
    # non-shape draft inputs as spec_off — the proposer just drafts
    # fewer tokens, the compiled verify shape never changes. None =
    # uncapped (--speculative-k governs).
    spec_drafted_total: int = 0
    spec_accepted_total: int = 0
    spec_k_cap: Optional[int] = None
    # Cluster KV economy (docs/kv_economy.md): parked in AWAITING_KV
    # at admission to probe the shared cache for this prompt's prefix
    # before prefill. Unlike a disagg handoff, a cold-start probe
    # degrades to compute IMMEDIATELY when the tier is unreachable —
    # nothing was shipped for it, so there is nothing to wait for.
    cold_start_probe: bool = False

    def transition(self, new_state: SequenceState) -> None:
        """The one sanctioned way to change ``state``. Validates the
        move against SEQUENCE_TRANSITIONS (same-state is a no-op, so
        idempotent callers like abort-on-already-aborted stay simple);
        an untabled pair raises instead of silently corrupting the
        lifecycle. The ``state-machine`` staticcheck rule flags any
        direct ``.state =`` write outside this method."""
        old = self.state
        if old == new_state:
            return
        if (old.value, new_state.value) not in _ALLOWED_TRANSITIONS:
            raise ValueError(
                f"untabled sequence transition {old.value} -> "
                f"{new_state.value} for {self.seq_id}; if this move is "
                "legitimate, add a row to SEQUENCE_TRANSITIONS (and "
                "docs/sequence_states.md)")
        self.state = new_state

    @property
    def num_generated(self) -> int:
        return self.num_prior_output_tokens + len(self.output_token_ids)

    @property
    def num_prompt_tokens(self) -> int:
        return len(self.prompt_token_ids)

    @property
    def total_len(self) -> int:
        return self.num_prompt_tokens + len(self.output_token_ids)

    @property
    def all_token_ids(self) -> List[int]:
        return self.prompt_token_ids + self.output_token_ids

    @property
    def prefill_done(self) -> bool:
        return self.num_computed_tokens >= self.num_prompt_tokens

    def remaining_prompt(self) -> int:
        return self.num_prompt_tokens - self.num_computed_tokens


def decode_budget(seq: "Sequence", max_model_len: int) -> int:
    """Tokens ``seq`` may still emit (max_tokens and model-length
    budgets). Single source of truth: the scheduler's page
    reservation, the host finish logic (scheduler._append_token), and
    the device decode burst (model_runner._decode_burst_impl) must all
    agree on this number or the burst could write past its pages."""
    return min(
        seq.sampling.max_tokens - seq.num_generated,
        max_model_len - seq.total_len,
    )
