"""Engine-side Prometheus exposition: vLLM-compatible histograms.

Real vLLM engines export request-latency histograms alongside the four
gauges our router scrapes (reference engine_stats.py:46-55 reads the
gauges; cluster Prometheus reads everything). This accumulator gives
the TPU engine the same surface: TTFT, inter-token latency and e2e
latency histograms plus token counters, rendered in Prometheus text
format by engine/server.py:/metrics.

Dependency-free (no prometheus_client in the engine hot path): fixed
buckets, plain counters, one lock.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Sequence


class Histogram:
    def __init__(self, buckets: Sequence[float]):
        self.buckets = list(buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # +inf tail
        self.total = 0.0
        self.n = 0

    def observe(self, value: float) -> None:
        self.total += value
        self.n += 1
        for i, b in enumerate(self.buckets):
            if value <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def render(self, name: str) -> List[str]:
        lines = [f"# TYPE {name} histogram"]
        cumulative = 0
        for b, c in zip(self.buckets, self.counts):
            cumulative += c
            lines.append(f'{name}_bucket{{le="{b}"}} {cumulative}')
        cumulative += self.counts[-1]
        lines.append(f'{name}_bucket{{le="+Inf"}} {cumulative}')
        lines.append(f"{name}_sum {self.total}")
        lines.append(f"{name}_count {self.n}")
        return lines


_TTFT_BUCKETS = (0.001, 0.005, 0.01, 0.02, 0.04, 0.06, 0.08, 0.1,
                 0.25, 0.5, 0.75, 1.0, 2.5, 5.0, 7.5, 10.0)
_ITL_BUCKETS = (0.001, 0.0025, 0.005, 0.0075, 0.01, 0.025, 0.05,
                0.075, 0.1, 0.2, 0.5, 1.0)
_E2E_BUCKETS = (0.3, 0.5, 0.8, 1.0, 1.5, 2.0, 2.5, 5.0, 10.0, 15.0,
                30.0, 60.0)


class EngineMetrics:
    """Request-lifecycle aggregates, updated on sequence completion."""

    def __init__(self):
        self._lock = threading.Lock()
        self.ttft = Histogram(_TTFT_BUCKETS)
        self.itl = Histogram(_ITL_BUCKETS)
        self.e2e = Histogram(_E2E_BUCKETS)
        # TTFT decomposition (vLLM names): time in the waiting queue
        # (arrival -> first scheduled) vs prefill compute (first
        # scheduled -> first token) — the honest split the round-2
        # review asked the stack to expose.
        self.queue_time = Histogram(_TTFT_BUCKETS)
        self.prefill_time = Histogram(_TTFT_BUCKETS)
        # Remaining request phases (docs/observability.md): decode
        # (first token -> finish) and, on disagg decode engines, the
        # AWAITING_KV park (handoff arrival -> admission — the phase
        # family view of the handoff-admission latency). Always
        # rendered (empty when unused) for a stable scrape surface.
        self.decode_time = Histogram(_E2E_BUCKETS)
        self.awaiting_kv_time = Histogram(_TTFT_BUCKETS)
        self.prompt_tokens_total = 0
        self.generation_tokens_total = 0
        self.requests_total: Dict[str, int] = {}
        # Speculative decoding (docs/speculative.md): cumulative draft
        # tokens proposed and accepted; acceptance rate =
        # accepted / drafted. Always rendered (0 when the feature is
        # off) so the router scraper sees a stable metric surface.
        self.spec_draft_tokens_total = 0
        self.spec_accepted_tokens_total = 0
        # Overlapped async pipeline (docs/async_pipeline.md): per-step
        # host vs device-wait seconds, the device-idle gap the
        # pipeline hides, and how many steps were dispatched ahead of
        # their predecessor's readback. Always rendered (0 when the
        # feature is off) for a stable scrape surface. Overlap
        # fraction = 1 - idle / host: ~0 synchronous, ->1 overlapped.
        self.step_host_seconds_total = 0.0
        self.step_device_wait_seconds_total = 0.0
        self.device_idle_seconds_total = 0.0
        self.pipeline_steps_total = 0
        self.pipeline_ahead_steps_total = 0
        self.async_inflight_depth = 0
        # Unified ragged step (docs/unified_step.md): the last mixed
        # dispatch's row occupancy split (gauges) plus cumulative row
        # totals so scrapers can derive the pad ratio
        # (pad_rows_total / rows_total) over any window. Always
        # rendered (0 when the feature is off) for a stable scrape
        # surface.
        self.last_prefill_rows = 0
        self.last_decode_rows = 0
        self.last_pad_rows = 0
        self.ragged_steps_total = 0
        self.ragged_rows_total = 0
        self.ragged_pad_rows_total = 0
        # Disaggregated serving (docs/disaggregation.md): latency from
        # a handoff submission arriving at a decode-role engine to the
        # sequence leaving AWAITING_KV (its pages became reachable or
        # it degraded to recompute). Always rendered (empty when the
        # engine never receives handoffs) for a stable scrape surface.
        self.handoff_latency = Histogram(_TTFT_BUCKETS)
        # QoS preempt-to-offload (docs/qos.md): time spent pulling a
        # preemption victim's pages back from the offload tier — the
        # page-transfer cost that replaced a prompt recompute. Always
        # rendered (empty without an offload tier) for a stable
        # scrape surface.
        self.preempt_restore_latency = Histogram(_TTFT_BUCKETS)

    def on_spec_step(self, drafted: int, accepted: int) -> None:
        """One speculative verify step's draft/accept counts."""
        with self._lock:
            self.spec_draft_tokens_total += drafted
            self.spec_accepted_tokens_total += accepted

    def on_ragged_step(self, prefill_rows: int, decode_rows: int,
                       pad_rows: int) -> None:
        """One unified ragged dispatch's row-occupancy split."""
        with self._lock:
            self.last_prefill_rows = prefill_rows
            self.last_decode_rows = decode_rows
            self.last_pad_rows = pad_rows
            self.ragged_steps_total += 1
            self.ragged_rows_total += (prefill_rows + decode_rows
                                       + pad_rows)
            self.ragged_pad_rows_total += pad_rows

    def on_pipeline_step(self, host_s: float, device_wait_s: float,
                         ahead: bool) -> None:
        """One engine step's host/device time split; ``ahead`` marks a
        step whose successor was dispatched before its readback."""
        with self._lock:
            self.step_host_seconds_total += max(0.0, host_s)
            self.step_device_wait_seconds_total += max(
                0.0, device_wait_s)
            self.pipeline_steps_total += 1
            if ahead:
                self.pipeline_ahead_steps_total += 1

    def on_device_idle(self, gap_s: float) -> None:
        """Device queue ran dry for ``gap_s`` before the next
        dispatch (the cost the async pipeline exists to remove)."""
        with self._lock:
            self.device_idle_seconds_total += max(0.0, gap_s)

    def set_inflight_depth(self, depth: int) -> None:
        with self._lock:
            self.async_inflight_depth = depth

    def on_handoff_admitted(self, latency_s: float) -> None:
        """One disagg handoff left AWAITING_KV after ``latency_s``."""
        with self._lock:
            self.handoff_latency.observe(max(0.0, latency_s))
            self.awaiting_kv_time.observe(max(0.0, latency_s))

    def on_preempt_restore(self, latency_s: float) -> None:
        """One offload-tier page restore completed (docs/qos.md)."""
        with self._lock:
            self.preempt_restore_latency.observe(max(0.0, latency_s))

    def on_decode_tokens(self, seq, n_tokens: int,
                         now: float) -> None:
        """Observe inter-token latency for one row's decode step.

        A step that emitted ``m`` tokens for the row observes m
        intervals of (now - prev)/m: multi-token steps (speculative
        verify, decode bursts) are credited at their true per-token
        cadence instead of one per-step or per-request mean."""
        if n_tokens <= 0:
            return
        prev = (seq.last_token_time
                if seq.last_token_time is not None
                else seq.first_token_time)
        seq.last_token_time = now
        if prev is None:
            return
        dt = max(0.0, now - prev) / n_tokens
        with self._lock:
            for _ in range(n_tokens):
                self.itl.observe(dt)

    def on_finished(self, seq) -> None:
        with self._lock:
            self.prompt_tokens_total += seq.num_prompt_tokens
            n_out = len(seq.output_token_ids)
            self.generation_tokens_total += n_out
            reason = (seq.finish_reason.value if seq.finish_reason
                      else "unknown")
            self.requests_total[reason] = (
                self.requests_total.get(reason, 0) + 1)
            if seq.first_token_time is not None:
                self.ttft.observe(
                    seq.first_token_time - seq.arrival_time)
                if seq.first_scheduled_time is not None:
                    self.queue_time.observe(
                        seq.first_scheduled_time - seq.arrival_time)
                    self.prefill_time.observe(
                        seq.first_token_time
                        - seq.first_scheduled_time)
                # Inter-token latency is observed per token as decode
                # steps complete (on_decode_tokens) — no per-request
                # mean here, which would double-count.
                if seq.finish_time is not None:
                    self.decode_time.observe(
                        seq.finish_time - seq.first_token_time)
            if seq.finish_time is not None:
                self.e2e.observe(seq.finish_time - seq.arrival_time)

    def render(self) -> List[str]:
        with self._lock:
            lines = self.ttft.render("vllm:time_to_first_token_seconds")
            lines += self.itl.render(
                "vllm:time_per_output_token_seconds")
            lines += self.e2e.render(
                "vllm:e2e_request_latency_seconds")
            lines += self.queue_time.render(
                "vllm:request_queue_time_seconds")
            lines += self.prefill_time.render(
                "vllm:request_prefill_time_seconds")
            lines += self.decode_time.render(
                "vllm:request_decode_time_seconds")
            lines += self.awaiting_kv_time.render(
                "vllm:request_awaiting_kv_time_seconds")
            lines += self.handoff_latency.render(
                "vllm:disagg_handoff_latency_seconds")
            lines += self.preempt_restore_latency.render(
                "vllm:preempt_restore_latency_seconds")
            lines += [
                "# TYPE vllm:prompt_tokens_total counter",
                f"vllm:prompt_tokens_total {self.prompt_tokens_total}",
                "# TYPE vllm:generation_tokens_total counter",
                ("vllm:generation_tokens_total "
                 f"{self.generation_tokens_total}"),
                ("# TYPE vllm:spec_decode_num_draft_tokens_total "
                 "counter"),
                ("vllm:spec_decode_num_draft_tokens_total "
                 f"{self.spec_draft_tokens_total}"),
                ("# TYPE vllm:spec_decode_num_accepted_tokens_total "
                 "counter"),
                ("vllm:spec_decode_num_accepted_tokens_total "
                 f"{self.spec_accepted_tokens_total}"),
                "# TYPE vllm:engine_step_host_seconds_total counter",
                ("vllm:engine_step_host_seconds_total "
                 f"{self.step_host_seconds_total}"),
                ("# TYPE vllm:engine_step_device_wait_seconds_total "
                 "counter"),
                ("vllm:engine_step_device_wait_seconds_total "
                 f"{self.step_device_wait_seconds_total}"),
                "# TYPE vllm:engine_device_idle_seconds_total counter",
                ("vllm:engine_device_idle_seconds_total "
                 f"{self.device_idle_seconds_total}"),
                "# TYPE vllm:engine_pipeline_steps_total counter",
                ("vllm:engine_pipeline_steps_total "
                 f"{self.pipeline_steps_total}"),
                ("# TYPE vllm:engine_pipeline_ahead_steps_total "
                 "counter"),
                ("vllm:engine_pipeline_ahead_steps_total "
                 f"{self.pipeline_ahead_steps_total}"),
                "# TYPE vllm:engine_async_inflight_depth gauge",
                ("vllm:engine_async_inflight_depth "
                 f"{self.async_inflight_depth}"),
                "# TYPE vllm:engine_step_prefill_rows gauge",
                ("vllm:engine_step_prefill_rows "
                 f"{self.last_prefill_rows}"),
                "# TYPE vllm:engine_step_decode_rows gauge",
                ("vllm:engine_step_decode_rows "
                 f"{self.last_decode_rows}"),
                "# TYPE vllm:engine_step_pad_rows gauge",
                ("vllm:engine_step_pad_rows "
                 f"{self.last_pad_rows}"),
                "# TYPE vllm:engine_ragged_steps_total counter",
                ("vllm:engine_ragged_steps_total "
                 f"{self.ragged_steps_total}"),
                "# TYPE vllm:engine_ragged_rows_total counter",
                ("vllm:engine_ragged_rows_total "
                 f"{self.ragged_rows_total}"),
                "# TYPE vllm:engine_ragged_pad_rows_total counter",
                ("vllm:engine_ragged_pad_rows_total "
                 f"{self.ragged_pad_rows_total}"),
            ]
            # vLLM's success counter tracks completed requests only;
            # aborts go to a separate failure counter so reference
            # dashboards don't overcount success.
            lines.append("# TYPE vllm:request_success_total counter")
            for reason, count in sorted(self.requests_total.items()):
                if reason == "abort":
                    continue
                lines.append(
                    'vllm:request_success_total'
                    f'{{finished_reason="{reason}"}} {count}')
            aborted = self.requests_total.get("abort", 0)
            if aborted:
                lines += [
                    "# TYPE vllm:request_failure_total counter",
                    'vllm:request_failure_total'
                    f'{{finished_reason="abort"}} {aborted}',
                ]
            return lines
