"""Remote shared KV cache server (the LMCache server analogue).

The reference deploys ``lmcache_experimental_server`` as a standalone
Deployment that multiple vLLM pods share KV through
(helm/templates/deployment-cache-server.yaml:1-52, tutorial 06). This is
our DCN-tier equivalent: a content-addressed page store over HTTP with
msgpack framing, LRU-bounded, shared by every engine pod configured
with ``--kv-remote-url``.

Run: ``python -m production_stack_tpu.engine.cache_server --port 8100``
"""

from __future__ import annotations

import argparse
import threading
from collections import OrderedDict

from aiohttp import web

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


class BlobStore:
    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._store: "OrderedDict[str, bytes]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def put(self, key: str, blob: bytes) -> None:
        with self._lock:
            old = self._store.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            while self._bytes + len(blob) > self.max_bytes and self._store:
                _, evicted = self._store.popitem(last=False)
                self._bytes -= len(evicted)
            if len(blob) <= self.max_bytes:
                self._store[key] = blob
                self._bytes += len(blob)

    def get(self, key: str):
        with self._lock:
            blob = self._store.get(key)
            if blob is not None:
                self._store.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
            return blob

    def contains(self, key: str) -> bool:
        with self._lock:
            return key in self._store

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._store),
                "bytes": self._bytes,
                "hits": self.hits,
                "misses": self.misses,
            }


def _validate_payload(blob: bytes):
    """Decode-side guard for inbound KV payloads.

    Returns an error string (-> 400) for anything that is not a
    well-formed per-array msgpack frame with allowlisted dtypes and
    shape-consistent buffers, so a corrupt or malicious payload can
    neither crash the server nor poison a pod restoring it.
    """
    import msgpack

    from production_stack_tpu.engine.offload import (
        ALLOWED_WIRE_DTYPES,
        _np_dtype,
    )
    try:
        obj = msgpack.unpackb(blob)
    except Exception:
        return "payload is not valid msgpack"
    if not isinstance(obj, dict) or not isinstance(
            obj.get("arrays"), list) or not obj["arrays"]:
        return "payload missing 'arrays' list"
    for a in obj["arrays"]:
        if not isinstance(a, dict):
            return "array entry is not a map"
        dtype_name = a.get("dtype")
        if dtype_name not in ALLOWED_WIRE_DTYPES:
            return f"dtype {dtype_name!r} not in allowlist"
        shape = a.get("shape")
        data = a.get("data")
        if (not isinstance(shape, list) or not isinstance(data, bytes)
                or not all(isinstance(d, int) and d >= 0
                           for d in shape)):
            return "array entry missing shape/data"
        n = _np_dtype(dtype_name).itemsize
        for d in shape:
            n *= d
        if n != len(data):
            return "array data size does not match shape/dtype"
    return None


# Upper bound on keys per batched GET: bounds the response to
# ~max page size x this many blobs and keeps one request from
# monopolising the store lock.
BATCH_GET_MAX_KEYS = 1024


def build_cache_server(max_bytes: int = 8 * 1024 ** 3) -> web.Application:
    store = BlobStore(max_bytes)

    async def put_kv(request: web.Request) -> web.Response:
        blob = await request.read()
        err = _validate_payload(blob)
        if err is not None:
            return web.json_response(
                {"error": {"message": err}}, status=400)
        store.put(request.match_info["key"], blob)
        return web.Response(status=200)

    async def get_kv(request: web.Request) -> web.Response:
        blob = store.get(request.match_info["key"])
        if blob is None:
            return web.Response(status=404)
        return web.Response(
            body=blob, content_type="application/octet-stream"
        )

    async def head_kv(request: web.Request) -> web.Response:
        if store.contains(request.match_info["key"]):
            return web.Response(status=200)
        return web.Response(status=404)

    async def batch_get_kv(request: web.Request) -> web.Response:
        """Many-page GET in one round trip (disagg decode restores:
        docs/disaggregation.md). Request: msgpack {"keys": [str,...]};
        response: msgpack {"blobs": [bytes|nil,...]} aligned to the
        request order, each blob the exact frame stored at PUT (so it
        was already validated by _validate_payload)."""
        import msgpack
        body = await request.read()
        try:
            obj = msgpack.unpackb(body)
        except Exception:
            return web.json_response(
                {"error": {"message": "body is not valid msgpack"}},
                status=400)
        keys = obj.get("keys") if isinstance(obj, dict) else None
        if (not isinstance(keys, list)
                or not all(isinstance(k, str) for k in keys)):
            return web.json_response(
                {"error": {"message": "body missing 'keys' list"}},
                status=400)
        if len(keys) > BATCH_GET_MAX_KEYS:
            return web.json_response(
                {"error": {"message":
                           f"too many keys (max {BATCH_GET_MAX_KEYS})"}},
                status=400)
        blobs = [store.get(k) for k in keys]
        return web.Response(
            body=msgpack.packb({"blobs": blobs}),
            content_type="application/octet-stream")

    async def health(request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    async def stats(request: web.Request) -> web.Response:
        return web.json_response(store.stats())

    async def metrics(request: web.Request) -> web.Response:
        s = store.stats()
        total = s["hits"] + s["misses"]
        lines = [
            "# TYPE kvcache:entries gauge",
            f"kvcache:entries {s['entries']}",
            "# TYPE kvcache:bytes gauge",
            f"kvcache:bytes {s['bytes']}",
            "# TYPE kvcache:hit_rate gauge",
            f"kvcache:hit_rate {(s['hits'] / total) if total else 0.0}",
            "",
        ]
        return web.Response(text="\n".join(lines),
                            content_type="text/plain")

    app = web.Application(client_max_size=256 * 1024 ** 2)
    app["store"] = store
    # Exact route first: /kv/batch_get must never resolve as a page
    # key (sha256 hex keys cannot collide with it anyway).
    app.router.add_post("/kv/batch_get", batch_get_kv)
    app.router.add_put("/kv/{key}", put_kv)
    app.router.add_head("/kv/{key}", head_kv)
    app.router.add_get("/kv/{key}", get_kv, allow_head=False)
    app.router.add_get("/health", health)
    app.router.add_get("/stats", stats)
    app.router.add_get("/metrics", metrics)
    return app


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(prog="tpu-kv-cache-server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8100)
    parser.add_argument("--max-bytes", type=int, default=8 * 1024 ** 3)
    args = parser.parse_args(argv)
    logger.info("KV cache server on %s:%d (budget %d MiB)",
                args.host, args.port, args.max_bytes // 2 ** 20)
    web.run_app(build_cache_server(args.max_bytes), host=args.host,
                port=args.port, print=None)


if __name__ == "__main__":
    main()
