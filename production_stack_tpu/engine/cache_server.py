"""Remote shared KV cache server (the LMCache server analogue).

The reference deploys ``lmcache_experimental_server`` as a standalone
Deployment that multiple vLLM pods share KV through
(helm/templates/deployment-cache-server.yaml:1-52, tutorial 06). This is
our DCN-tier equivalent: a content-addressed page store over HTTP with
msgpack framing, shared by every engine pod configured with
``--kv-remote-url``.

The store behind the routes is the MANAGED cluster prefix cache
(kvecon/cluster_cache.py, docs/kv_economy.md): admission by
distinct-requester demand promotion (PUT answers 200 with an
``{"admitted": bool}`` verdict; probe and fetch misses record demand),
TTL + LRU eviction of coldest chains whole under capacity watermarks,
and per-chain metadata. ``build_cache_server``'s defaults
(admit_hits=1, no TTL, watermarks 1.0) reproduce the legacy
store-on-first-write LRU; the CLI defaults to the managed policy.

Run: ``python -m production_stack_tpu.engine.cache_server --port 8100``
"""

from __future__ import annotations

import argparse

from aiohttp import web

from production_stack_tpu.kvecon.cluster_cache import (
    CHAIN_HEADER,
    REQUESTER_HEADER,
    ManagedKVStore,
)
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


def _validate_payload(blob: bytes):
    """Decode-side guard for inbound KV payloads.

    Returns an error string (-> 400) for anything that is not a
    well-formed per-array msgpack frame with allowlisted dtypes and
    shape-consistent buffers, so a corrupt or malicious payload can
    neither crash the server nor poison a pod restoring it.
    """
    import msgpack

    from production_stack_tpu.engine.offload import (
        ALLOWED_WIRE_DTYPES,
        _np_dtype,
    )
    try:
        obj = msgpack.unpackb(blob)
    except Exception:
        return "payload is not valid msgpack"
    if not isinstance(obj, dict) or not isinstance(
            obj.get("arrays"), list) or not obj["arrays"]:
        return "payload missing 'arrays' list"
    for a in obj["arrays"]:
        if not isinstance(a, dict):
            return "array entry is not a map"
        dtype_name = a.get("dtype")
        if dtype_name not in ALLOWED_WIRE_DTYPES:
            return f"dtype {dtype_name!r} not in allowlist"
        shape = a.get("shape")
        data = a.get("data")
        if (not isinstance(shape, list) or not isinstance(data, bytes)
                or not all(isinstance(d, int) and d >= 0
                           for d in shape)):
            return "array entry missing shape/data"
        n = _np_dtype(dtype_name).itemsize
        for d in shape:
            n *= d
        if n != len(data):
            return "array data size does not match shape/dtype"
    return None


def _wire_dtype(blob: bytes) -> str:
    """First array's dtype, for the chain metadata (payload was
    already validated)."""
    import msgpack
    try:
        return str(msgpack.unpackb(blob)["arrays"][0]["dtype"])
    except Exception:
        return ""


# Upper bound on keys per batched GET: bounds the response to
# ~max page size x this many blobs and keeps one request from
# monopolising the store lock.
BATCH_GET_MAX_KEYS = 1024


def build_cache_server(max_bytes: int = 8 * 1024 ** 3,
                       admit_hits: int = 1,
                       ttl_s: float = 0.0,
                       watermark_high: float = 1.0,
                       watermark_low: float = 1.0,
                       clock=None) -> web.Application:
    store = ManagedKVStore(
        max_bytes, admit_hits=admit_hits, ttl_s=ttl_s,
        watermark_high=watermark_high, watermark_low=watermark_low,
        **({"clock": clock} if clock is not None else {}))

    def _requester(request: web.Request) -> str:
        # Fall back to the peer address so legacy clients without the
        # header still count as (coarse) distinct requesters.
        rid = request.headers.get(REQUESTER_HEADER, "")
        if rid:
            return rid
        peer = request.transport.get_extra_info("peername") \
            if request.transport else None
        return peer[0] if isinstance(peer, tuple) else "anon"

    async def put_kv(request: web.Request) -> web.Response:
        blob = await request.read()
        err = _validate_payload(blob)
        if err is not None:
            return web.json_response(
                {"error": {"message": err}}, status=400)
        key = request.match_info["key"]
        chain = request.headers.get(CHAIN_HEADER) or None
        if chain:
            # Demand recorded against the bare key (probe misses don't
            # know the chain) merges into the chain before the verdict.
            store.associate(key, chain)
        admitted = store.put(
            key, blob, chain_id=chain,
            requester=_requester(request),
            kv_dtype=_wire_dtype(blob))
        return web.json_response({"admitted": admitted})

    async def get_kv(request: web.Request) -> web.Response:
        blob = store.get(request.match_info["key"],
                         requester=_requester(request))
        if blob is None:
            return web.Response(status=404)
        return web.Response(
            body=blob, content_type="application/octet-stream"
        )

    async def head_kv(request: web.Request) -> web.Response:
        if store.contains(request.match_info["key"],
                          requester=_requester(request)):
            return web.Response(status=200)
        return web.Response(status=404)

    async def batch_get_kv(request: web.Request) -> web.Response:
        """Many-page GET in one round trip (disagg decode restores:
        docs/disaggregation.md). Request: msgpack {"keys": [str,...]};
        response: msgpack {"blobs": [bytes|nil,...]} aligned to the
        request order, each blob the exact frame stored at PUT (so it
        was already validated by _validate_payload)."""
        import msgpack
        body = await request.read()
        try:
            obj = msgpack.unpackb(body)
        except Exception:
            return web.json_response(
                {"error": {"message": "body is not valid msgpack"}},
                status=400)
        keys = obj.get("keys") if isinstance(obj, dict) else None
        if (not isinstance(keys, list)
                or not all(isinstance(k, str) for k in keys)):
            return web.json_response(
                {"error": {"message": "body missing 'keys' list"}},
                status=400)
        if len(keys) > BATCH_GET_MAX_KEYS:
            return web.json_response(
                {"error": {"message":
                           f"too many keys (max {BATCH_GET_MAX_KEYS})"}},
                status=400)
        rid = _requester(request)
        blobs = [store.get(k, requester=rid) for k in keys]
        return web.Response(
            body=msgpack.packb({"blobs": blobs}),
            content_type="application/octet-stream")

    async def health(request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    async def stats(request: web.Request) -> web.Response:
        return web.json_response(store.stats())

    async def metrics(request: web.Request) -> web.Response:
        s = store.stats()
        lines = [
            "# TYPE kvcache:entries gauge",
            f"kvcache:entries {s['entries']}",
            "# TYPE kvcache:bytes gauge",
            f"kvcache:bytes {s['bytes']}",
            "# TYPE kvcache:hit_rate gauge",
            f"kvcache:hit_rate {s['hit_rate']}",
            "# TYPE kvcache:chains gauge",
            f"kvcache:chains {s['chains']}",
            "# TYPE kvcache:hits_total counter",
            f"kvcache:hits_total {s['hits']}",
            "# TYPE kvcache:misses_total counter",
            f"kvcache:misses_total {s['misses']}",
            "# TYPE kvcache:admissions_total counter",
            f"kvcache:admissions_total {s['admissions']}",
            "# TYPE kvcache:evictions_total counter",
            f"kvcache:evictions_total {s['evictions']}",
            "# TYPE kvcache:rejected_puts_total counter",
            f"kvcache:rejected_puts_total {s['rejected_puts']}",
            "",
        ]
        return web.Response(text="\n".join(lines),
                            content_type="text/plain")

    app = web.Application(client_max_size=256 * 1024 ** 2)
    app["store"] = store
    # Exact route first: /kv/batch_get must never resolve as a page
    # key (sha256 hex keys cannot collide with it anyway).
    app.router.add_post("/kv/batch_get", batch_get_kv)
    app.router.add_put("/kv/{key}", put_kv)
    app.router.add_head("/kv/{key}", head_kv)
    app.router.add_get("/kv/{key}", get_kv, allow_head=False)
    app.router.add_get("/health", health)
    app.router.add_get("/stats", stats)
    app.router.add_get("/metrics", metrics)
    return app


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(prog="tpu-kv-cache-server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8100)
    parser.add_argument("--max-bytes", type=int, default=8 * 1024 ** 3)
    # Managed-cache policy (docs/kv_economy.md). The CLI defaults are
    # the managed economy; pass --kv-admit-hits 1 --kv-ttl-s 0
    # --kv-watermark-high 1.0 --kv-watermark-low 1.0 for the legacy
    # store-on-first-write LRU.
    parser.add_argument(
        "--kv-admit-hits", type=int, default=2,
        help="Distinct requesters that must want a chain before its "
             "pages are stored")
    parser.add_argument(
        "--kv-ttl-s", type=float, default=900.0,
        help="Seconds an idle chain survives before TTL eviction "
             "(0 disables)")
    parser.add_argument(
        "--kv-watermark-high", type=float, default=0.95,
        help="Stored-bytes fraction of --max-bytes that triggers "
             "coldest-chain eviction")
    parser.add_argument(
        "--kv-watermark-low", type=float, default=0.80,
        help="Fraction eviction drains down to once triggered")
    args = parser.parse_args(argv)
    logger.info(
        "KV cache server on %s:%d (budget %d MiB, admit_hits=%d, "
        "ttl=%gs, watermarks %.2f/%.2f)",
        args.host, args.port, args.max_bytes // 2 ** 20,
        args.kv_admit_hits, args.kv_ttl_s,
        args.kv_watermark_high, args.kv_watermark_low)
    web.run_app(
        build_cache_server(
            args.max_bytes, admit_hits=args.kv_admit_hits,
            ttl_s=args.kv_ttl_s,
            watermark_high=args.kv_watermark_high,
            watermark_low=args.kv_watermark_low),
        host=args.host, port=args.port, print=None)


if __name__ == "__main__":
    main()
