"""Model runner: owns device state and the compiled step functions.

Compilation strategy (SURVEY.md §7 hard part (a)): prefill chunks are
padded to power-of-two buckets and decode runs at a fixed slot width, so
the engine touches a small closed set of shapes; each shape jit-compiles
once and is cached by XLA thereafter. KV caches are donated through
every step so the arrays are updated in place in HBM.
"""

from __future__ import annotations

import functools
import json
import os
import time
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.perf_observatory import (
    InstrumentedJit,
    PerfObservatory,
)
from production_stack_tpu.engine.scheduler import DecodePlan, PrefillPlan
from production_stack_tpu.engine.sequence import Sequence, decode_budget
from production_stack_tpu.models.registry import get_model
from production_stack_tpu.ops.attention import write_to_pages
from production_stack_tpu.ops.quant_kv import (
    QuantKV,
    quant_cache_struct,
    quant_cache_zeros,
)
from production_stack_tpu.ops.sampling import (
    apply_penalties,
    sample_tokens,
    spec_verify,
    token_logprobs,
)
from production_stack_tpu.parallel.mesh import (
    shard_cache,
    shard_params,
)
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

# Fixed per-row stop-set width for the decode burst: one compiled
# shape regardless of batch composition (a data-dependent width would
# recompile the fused K-step program mid-serving). Requests with more
# stop ids than this still finish correctly — the host enforces the
# full set; the burst merely speculates a little further.
STOP_SET_WIDTH = 16

# Measured decode-kernel verdict (benchmarks/results/
# kernel_microbench.json, TPU v5e, 2026-07-31, post-aliasing-fix):
# the Pallas decode kernel loses to the XLA gather path at every
# serving shape measured — 0.42-0.65x at ctx 2k-16k for batch 8-32 —
# and wins only the single thin cell batch=8/ctx=512, where decode is
# cheap anyway. The pre-fix ">=8k crossover" no longer exists, so
# attention_impl='auto' serves XLA decode at ALL shapes; an explicit
# attention_impl='pallas' still forces the kernel (operator override,
# e.g. for re-measurement with benchmarks/kernel_microbench.py).
# Prefill is the opposite story: the Pallas prefill kernel wins
# every measured cell (1.25-2.3x), so 'auto' keeps serving it.
PALLAS_DECODE_IN_AUTO = False

# Compiled top-logprobs width: OpenAI allows top_logprobs 0-20 but a
# per-request width would compile a program per value; requests are
# served min(requested, TOP_LOGPROBS_WIDTH) alternatives from one
# compiled shape. Sized to the OpenAI maximum so the server never
# silently returns fewer alternatives than requested (the server also
# rejects top_logprobs > 20 with a 400).
TOP_LOGPROBS_WIDTH = 20

# Model families served by the deferred-KV-write burst (the kv_tail
# path exists in models/llama.py, which also serves mistral/qwen2).
DEFERRED_KV_FAMILIES = ("llama", "mistral", "qwen2")


def deferred_kv_eligible(architecture: str, decode_steps: int,
                         attention_impl: str, pipeline_parallel: int = 1,
                         context_parallel: int = 1,
                         speculative_k: int = 0) -> bool:
    """The ONE eligibility predicate for deferred KV writes.

    Used by the runner's capability guard (which raises on explicit
    ineligible 'on'), the server's '--deferred-kv-writes auto'
    resolution, and bench.py's impl gating — one definition so the
    three call sites cannot drift (e.g. re-enabling Pallas decode in
    'auto' or adding an exclusion must flow to all of them).
    Speculative decoding excludes deferral: the verify step must
    write draft KV eagerly so later draft positions attend to
    earlier ones (docs/speculative.md §interactions)."""
    return (decode_steps > 1
            and architecture in DEFERRED_KV_FAMILIES
            and attention_impl in ("xla", "auto")
            and pipeline_parallel == 1
            and context_parallel == 1
            and speculative_k == 0)


def async_scheduling_eligible(decode_steps: int, speculative_k: int,
                              distributed: bool = False) -> bool:
    """The ONE eligibility predicate for the overlapped async
    execution pipeline (docs/async_pipeline.md).

    Used by EngineConfig's hard validation error message, the server's
    '--async-scheduling auto' resolution and bench.py's pass gating —
    one definition so the call sites cannot drift (the
    deferred_kv_eligible pattern). The pipeline's plan-ahead step
    assumes every running row commits exactly one token per dispatch,
    so multi-step bursts and speculative verify (data-dependent commit
    counts) are out; multihost serving is out because the step
    broadcast ships host-resident numpy payloads, while the ahead
    dispatch feeds device-resident arrays forward."""
    return (decode_steps == 1 and speculative_k == 0
            and not distributed)


def unified_step_eligible(pipeline_parallel: int = 1,
                          context_parallel: int = 1,
                          distributed: bool = False,
                          engine_role: str = "both") -> bool:
    """The ONE eligibility predicate for the unified ragged step
    (docs/unified_step.md).

    Used by the server's '--unified-step auto' resolution and
    bench.py's pass gating — one definition so the call sites cannot
    drift (the deferred_kv_eligible pattern). The pp and cp runners
    now execute the ragged [R, W] block natively — pipeline stages
    thread the per-row descriptor triple through their microbatch
    handoffs, and the sp runner shards the W axis
    (docs/parallelism.md) — so pp/cp no longer disqualify. Still out:
    the multihost bridge broadcasts bimodal payload kinds, and a
    disaggregated role engine by construction never holds prefill and
    decode work at once, so neither can mix rows. The pp/cp arguments
    stay in the signature so the call sites (server resolution, bench
    gating) keep passing their full config — a future disqualifier
    lands in one place."""
    del pipeline_parallel, context_parallel  # no longer disqualifying
    return not distributed and engine_role == "both"


def pallas_backend_error(page_size: int) -> Optional[str]:
    """The ONE Mosaic backend rule gating every Pallas attention site.

    The kernels DMA [head_dim, page_size] page slices out of HBM;
    Mosaic requires the minor dim be lane-tile (128) aligned. This is
    a *backend* rule the Python lowering probes cannot see (it fires
    at Mosaic machine-code compile), so it is gated explicitly —
    and in ONE place, used by all three resolution sites
    (decode/prefill, spec verify, unified ragged), mirroring
    deferred_kv_eligible: a backend rule that drifts across sites is
    how the unified path briefly resolved independently of the
    decode/prefill gate. Returns a reason string when Pallas cannot
    serve, None when the backend rule is satisfied."""
    if page_size % 128:
        return ("Pallas attention needs page_size %% 128 == 0 "
                "(got %d)" % page_size)
    return None


# PSTPU_TIMING=1: log every dispatch's wall time (dispatch ->
# device_get of the sampled tokens, i.e. including device execution)
# to stderr as "timing <kind> t=<window|bucket> <seconds>". The only
# reliable sync on a tunneled device is a host transfer, so these
# walls include one ~RTT; per-phase aggregation is what they're for.
# Timing mode forces a sync even on prefill dispatches that would
# otherwise return async (no last chunk), so every logged wall really
# contains its device execution.
_TIMING = (os.environ.get("PSTPU_TIMING", "0").strip().lower()
           in ("1", "true", "yes", "on"))


def _timing_log(kind: str, t: int, wall: float) -> None:
    logger.info("timing %s t=%d %.4f", kind, t, wall)


def _as_device(x):
    """Identity for arrays already on device; transfer otherwise.

    Payload entries arrive as jax.Arrays on the local dispatch path
    (one fused device_put upstream) but as numpy on the multihost
    broadcast path. ``jnp.asarray`` is semantically a no-op for the
    former yet costs ~0.1 ms of dtype canonicalization per call —
    ~1 ms per decode step across a payload — so skip it.
    """
    return x if isinstance(x, jax.Array) else jnp.asarray(x)


def prefill_buckets(chunk_size: int) -> List[int]:
    buckets, b = [], 16
    while b < chunk_size:
        buckets.append(b)
        b *= 2
    buckets.append(chunk_size)
    return buckets


class DecodeStepHandle:
    """One dispatched-but-unread single-step decode program.

    Under JAX async dispatch the compiled program is already running
    (or queued) on device; ``token_source`` exposes the sampled-token
    device array so the NEXT step can consume it without a host round
    trip, and ``result()`` performs the step's ONE blocking host read
    — a single fused device_get of the sampled tokens plus, when
    requested, all three logprob arrays — parsed exactly like the
    synchronous path so sync and async consumers share one format.
    """

    is_spec = False

    def __init__(self, runner: "ModelRunner", rows, sampled,
                 want_lp: bool):
        self.runner = runner
        # List[Optional[Sequence]]: None rows are plan-ahead slots
        # whose sequence was already known to finish (dispatched as
        # masked pad rows so row alignment with token_source holds).
        self.rows = rows
        self.sampled = sampled
        self.want_lp = want_lp
        # Set by the engine when this step was dispatched ahead of an
        # unread speculative verify step: expected_lens[i] is the
        # committed length row i must reach at completion for this
        # step's assume-one-token planning to have been right; a
        # mismatch (the verify accepted >= 1 draft) drops the row's
        # token through the stale-token path (docs/unified_step.md).
        self.expected_lens = None

    @property
    def token_source(self) -> jax.Array:
        """The [B] sampled-token device array (async feed-forward)."""
        return self.sampled[0] if self.want_lp else self.sampled

    def result(self) -> Tuple[List[List[int]], Optional[list]]:
        """Block on the step's one fused device_get and parse."""
        host = jax.device_get(self.sampled)
        n = len(self.rows)
        if not self.want_lp:
            return [[int(host[i])] for i in range(n)], None
        toks, slp, tids, tlps = host
        token_lists = [[int(toks[i])] for i in range(n)]
        lp_lists = [
            [self.runner._lp_entry(row, slp[i], tids[i], tlps[i])
             if row is not None and row.sampling.logprobs else None]
            for i, row in enumerate(self.rows)
        ]
        return token_lists, lp_lists


class SpecStepHandle:
    """One dispatched-but-unread speculative verify step.

    The async pipeline treats a verify step as a decode step with a
    data-dependent commit count (1..S tokens per row).
    ``token_source`` exposes the [B] device array of FIRST emitted
    tokens (e_0): whatever the acceptance turns out to be, e_0 is
    committed, and the token the assume-one-token ahead dispatch
    feeds at position L writes position L's CORRECT KV in both cases
    — if the first draft was accepted the write is bit-identical to
    the verify step's own, and if it was rejected the write repairs
    the junk the rejected draft left there (docs/unified_step.md
    §spec-under-async). ``result()`` performs the step's one blocking
    device_get and parses exactly like the synchronous spec path.
    """

    is_spec = True
    # Verify steps are never themselves dispatched ahead of an unread
    # verify step (the engine breaks the pipeline instead), so the
    # stale-drop marker is always unset here.
    expected_lens = None

    def __init__(self, runner: "ModelRunner", rows, drafts, sampled,
                 want_lp: bool):
        self.runner = runner
        self.rows = rows  # List[Sequence], no None slots
        self.drafts = drafts  # per-row draft lists (parallel to rows)
        self.sampled = sampled
        self.want_lp = want_lp

    @property
    def token_source(self) -> jax.Array:
        """[B] device array of each row's first emitted token."""
        out = self.sampled[0] if self.want_lp else self.sampled
        return out[:, 0]

    def result(self) -> Tuple[List[List[int]], Optional[list]]:
        host = jax.device_get(self.sampled)
        n = len(self.rows)
        if not self.want_lp:
            return [[int(t) for t in host[i] if t >= 0]
                    for i in range(n)], None
        toks, slp, tids, tlps = host
        s = toks.shape[1]
        token_lists, lp_lists = [], []
        for i, seq in enumerate(self.rows):
            row_t, row_l = [], []
            for j in range(s):
                if toks[i, j] < 0:
                    break
                row_t.append(int(toks[i, j]))
                row_l.append(
                    self.runner._lp_entry(seq, slp[i, j], tids[i, j],
                                          tlps[i, j])
                    if seq.sampling.logprobs else None)
            token_lists.append(row_t)
            lp_lists.append(row_l)
        return token_lists, lp_lists


class ModelRunner:
    def __init__(self, config: EngineConfig, mesh=None,
                 params=None):
        self.config = config
        self.mesh = mesh
        model_config = config.model
        # int8 paged KV (docs/kv_quantization.md): pages stored as
        # QuantKV pytrees (int8 data + per-slot f32 scales); the write
        # path quantizes in-graph and the attention impls dequantize
        # in-kernel. Resolved once here — everything downstream
        # (cache creation, lowering probes, read/write_page, offload
        # payload arity) keys off this flag.
        self.kv_quantized = config.cache.resolved_kv_dtype() == "int8"
        if config.cache.cache_layout == "auto":
            # Measured default (benchmarks/results/decode_probe.json,
            # TPU v5e, 2026-07-31): per_layer decode bursts run 2.0x
            # faster than the stacked layout (13.5 vs 27.4 ms per
            # token-step at the 1B bench config) and the engine bench
            # follows (11.07 vs 5.94 req/s). pp shards the stacked L
            # axis and the sp ring walks the stacked cache, so those
            # configs resolve to stacked.
            config.cache.cache_layout = (
                "stacked"
                if (config.parallel.pipeline_parallel_size > 1
                    or config.parallel.context_parallel_size > 1)
                else "per_layer")
        auto_impl = model_config.attention_impl == "auto"
        if auto_impl:
            model_config.attention_impl = (
                "xla" if jax.default_backend() == "cpu" else "pallas"
            )
        if (model_config.attention_impl == "pallas"
                and jax.default_backend() != "cpu"):
            # Per-kernel Mosaic lowering probe at the engine's real
            # shapes: decode and prefill degrade to XLA independently
            # (round-2 failure mode was a *global* fallback that threw
            # away the working decode kernel when prefill didn't
            # compile). Lowering runs Pallas's Mosaic rules (tiling,
            # layouts, scalar prefetch) without burning a full compile.
            # Under ``auto`` the choice is additionally *empirical*:
            # the measured-winner table (kernel microbench) decides,
            # not lowering success alone. An explicit "pallas" skips
            # the table (operator override).
            self._resolve_pallas_impls(model_config, config,
                                       empirical=auto_impl)
        logger.info(
            "Attention impls: decode=%s prefill=%s",
            model_config.attention_impl_decode
            or model_config.attention_impl,
            model_config.attention_impl_prefill
            or model_config.attention_impl)
        self._init_fn, self._forward = get_model(model_config)

        pp = config.parallel.pipeline_parallel_size
        if pp > 1:
            # Pipeline-parallel serving: stages over the mesh's 'pp'
            # axis replace the plain layer scan
            # (parallel/pipeline_serving.py).
            if mesh is None or "pp" not in mesh.axis_names \
                    or mesh.shape["pp"] != pp:
                raise ValueError(
                    "pipeline_parallel_size needs a mesh with a 'pp' "
                    f"axis of size {pp} (parallel.mesh.build_mesh)")
            from production_stack_tpu.parallel.pipeline_serving import (
                PP_FAMILIES,
                pp_paged_forward,
            )
            if model_config.architecture not in PP_FAMILIES:
                raise NotImplementedError(
                    "pipeline parallelism serves "
                    f"{'/'.join(PP_FAMILIES)} "
                    f"(got {model_config.architecture!r})")
            if model_config.num_hidden_layers % pp:
                raise ValueError(
                    f"layers {model_config.num_hidden_layers} must "
                    f"divide by pipeline_parallel_size {pp}")
            tp = config.parallel.tensor_parallel_size
            if tp > 1 and (model_config.num_key_value_heads % tp
                           or model_config.num_attention_heads % tp):
                raise ValueError(
                    "pp x tp needs attention/kv heads divisible by "
                    f"tensor_parallel_size {tp}")
            self._forward = functools.partial(pp_paged_forward,
                                              mesh=mesh)

        cp = config.parallel.context_parallel_size
        self._sp_size = cp
        if cp > 1:
            # Context-parallel prefill: long prompts shard their
            # sequence over the 'sp' mesh axis
            # (parallel/context_serving.py).
            from production_stack_tpu.parallel.context_serving import (
                SP_FAMILIES,
            )
            if mesh is None or "sp" not in mesh.axis_names \
                    or mesh.shape["sp"] != cp:
                raise ValueError(
                    "context_parallel_size needs a mesh with an 'sp' "
                    f"axis of size {cp} (parallel.mesh.build_mesh)")
            if model_config.architecture not in SP_FAMILIES:
                raise NotImplementedError(
                    "context parallelism serves "
                    f"{'/'.join(SP_FAMILIES)} "
                    f"(got {model_config.architecture!r})")
            if config.parallel.pipeline_parallel_size > 1:
                raise NotImplementedError(
                    "context parallelism with pipeline parallelism "
                    "(sp composes with tp; pp shards the layer axis "
                    "the sp prefill walks in full)")
            sp_tp = config.parallel.tensor_parallel_size
            if sp_tp > 1 and (
                    model_config.num_attention_heads % sp_tp
                    or model_config.num_key_value_heads % sp_tp):
                raise ValueError(
                    "sp x tp needs attention/kv heads divisible by "
                    f"tensor_parallel_size {sp_tp}")
            # Ragged unified / spec-verify dispatches on the cp
            # runner shard their W (token) axis over 'sp'
            # (context_serving.shard_w_forward): multi-token rows
            # split across the ring devices instead of replicating
            # the whole [R, W] block per device. Single-token decode
            # dispatches pass through unsharded.
            from production_stack_tpu.parallel.context_serving import (
                shard_w_forward,
            )
            self._forward = shard_w_forward(self._forward, mesh)

        self._deferred = config.scheduler.deferred_kv_writes
        if self._deferred:
            # Deferred per-burst KV writes (ops/attention.write_to_tail
            # + the kv_tail path in models/llama.forward): motivated by
            # the round-5 ablation — the per-step scatter + same-buffer
            # gather interaction costs ~4.4 of 8.3 ms/step (XLA
            # copy-insertion). Llama-family single-runner decode only;
            # reject loudly otherwise. The SAME predicate drives the
            # server's and bench's 'auto' resolution
            # (deferred_kv_eligible) — keep them in lockstep.
            if config.scheduler.decode_steps <= 1:
                raise ValueError(
                    "deferred_kv_writes needs decode_steps > 1 (the "
                    "tail flushes once per multi-step burst)")
            if (config.parallel.pipeline_parallel_size > 1
                    or self._sp_size > 1):
                raise NotImplementedError(
                    "deferred_kv_writes with pipeline/context "
                    "parallelism (the pp/sp runners use their own "
                    "burst bodies)")
            if model_config.architecture not in DEFERRED_KV_FAMILIES:
                raise NotImplementedError(
                    "deferred_kv_writes serves the llama family (got "
                    f"{model_config.architecture!r})")
            decode_impl = (model_config.attention_impl_decode
                           or model_config.attention_impl)
            if decode_impl not in ("xla", "auto"):
                raise NotImplementedError(
                    "deferred_kv_writes uses the XLA paged+tail "
                    f"attention path (decode impl {decode_impl!r})")

        if params is None and model_config.quantization == "int8":
            # Direct int8 init: full-precision init + quantize peaks
            # at 3x the serving footprint on device and OOMs the 8B
            # config on a 16 GB chip (see init_random_quantized).
            from production_stack_tpu.engine.quantization import (
                init_random_quantized,
            )
            logger.info("Initializing random int8 weights for %s",
                        model_config.name)
            params = init_random_quantized(
                self._init_fn, model_config, config.seed)
        elif params is None:
            logger.info("Initializing random weights for %s",
                        model_config.name)
            params = self._init_fn(
                model_config, jax.random.PRNGKey(config.seed)
            )
        elif model_config.quantization == "int8":
            from production_stack_tpu.engine.quantization import (
                has_quantized_leaves,
                quantize_params,
            )
            if not has_quantized_leaves(params):
                logger.info("Quantizing projection weights to int8 "
                            "(weight-only)")
                params = quantize_params(params, model_config)
        self.params = shard_params(params, model_config, mesh)

        # Device performance observatory (engine/perf_observatory.py):
        # exact param-tree sizes (array metadata only — no host
        # reads), the real device kind for the peak-FLOPs table, and
        # the resolved attention impls so the silent XLA fallback is
        # an alarmable gauge rather than a log line. Set to None to
        # disable every hook (the parity tests pin that path).
        _leaves = jax.tree_util.tree_leaves(self.params)
        try:
            _device_kind = getattr(jax.devices()[0], "device_kind", "")
        except Exception:
            _device_kind = ""
        self.observatory = PerfObservatory(
            config,
            param_count=sum(int(getattr(x, "size", 0))
                            for x in _leaves),
            params_bytes=sum(int(getattr(x, "nbytes", 0))
                             for x in _leaves),
            device_kind=_device_kind)
        self.observatory.set_attention_impl(
            "decode", model_config.attention_impl_decode
            or model_config.attention_impl)
        self.observatory.set_attention_impl(
            "prefill", model_config.attention_impl_prefill
            or model_config.attention_impl)

        # Head-major paged cache: [L, kv_heads, pages, d, page_size].
        # The kv axis is major so TP shards a leading axis; pages are
        # token-minor so the Pallas kernels DMA (d, 128)-tile-aligned
        # page slices straight out of HBM (ops/paged_attention_pallas).
        cache_shape = (
            model_config.num_hidden_layers,
            model_config.num_key_value_heads,
            config.cache.num_pages,
            model_config.head_dim,
            config.cache.page_size,
        )
        dtype = model_config.jax_dtype

        def _fresh_cache(shape):
            if self.kv_quantized:
                return shard_cache(quant_cache_zeros(shape), mesh)
            return shard_cache(jnp.zeros(shape, dtype), mesh)

        self.cache_layout = config.cache.cache_layout
        if self.cache_layout == "per_layer":
            # A tuple of L per-layer buffers instead of one stacked
            # array: scatters/kernels touch one layer's buffer and
            # donation aliases 1:1 (the round-3 decode-roofline
            # experiment — models/llama.py cached_attention).
            if (config.parallel.pipeline_parallel_size > 1
                    or self._sp_size > 1):
                raise NotImplementedError(
                    "cache_layout='per_layer' with pipeline/context "
                    "parallelism (pp shards the stacked L axis; use "
                    "the stacked layout)")
            self.k_cache = tuple(
                _fresh_cache(cache_shape[1:])
                for _ in range(model_config.num_hidden_layers))
            self.v_cache = tuple(
                _fresh_cache(cache_shape[1:])
                for _ in range(model_config.num_hidden_layers))
        elif self.cache_layout == "stacked":
            self.k_cache = _fresh_cache(cache_shape)
            self.v_cache = _fresh_cache(cache_shape)
        else:
            raise ValueError(
                "cache.cache_layout must be 'auto', 'stacked' or "
                f"'per_layer' (got {self.cache_layout!r})")

        self.max_pages_per_seq = config.scheduler.max_pages_per_seq(
            config.cache.page_size
        )
        self.decode_width = config.scheduler.max_num_seqs
        self.prefill_width = config.scheduler.prefill_batch_size
        self._buckets = prefill_buckets(
            config.scheduler.prefill_chunk_size
        )
        self._rng = jax.random.PRNGKey(config.seed + 1)
        # Reused host staging buffers for the single-step decode
        # payload (dispatch_decode): the per-step numpy allocation
        # shower is replaced by in-place fills + ONE fused
        # jax.device_put of the whole input set. DOUBLE-buffered
        # because the CPU backend may alias numpy memory into the
        # device buffer zero-copy: a buffer set is refilled only
        # after the step that consumed it has been completed
        # (pipeline depth is 1, and the engine reads step N's result
        # before dispatching N+2 — so set parity N mod 2 is free by
        # the time it is reused).
        self._decode_staging = None
        self._staging_idx = 0
        # (signature, {name: device array}) of the last dispatch's
        # static per-row inputs; reused while the row set is unchanged
        # (see dispatch_decode).
        self._decode_static_cache = None
        # Multihost step broadcast (parallel/distributed.py); host 0's
        # engine sets this so every dispatch is mirrored to workers.
        self.bridge = None
        # Embedder for /v1/embeddings|score|rerank; in multihost mode
        # every host builds one at startup so KIND_EMBED payloads can
        # be executed slice-wide (server.py main, --distributed).
        self.embedder = None

        # Multi-LoRA: device-resident adapter stacks; a per-row slot-id
        # vector selects the adapter (engine/lora.py). None when off so
        # the base model compiles with zero LoRA overhead.
        self.lora_registry = None
        if config.lora.enable:
            from production_stack_tpu.engine.lora import LoRARegistry
            self.lora_registry = LoRARegistry(
                model_config, config.lora.max_loras,
                config.lora.max_lora_rank,
            )

        self._step_jit = InstrumentedJit("step", jax.jit(
            self._step_impl,
            static_argnames=("sample_index_mode", "want_logprobs"),
            donate_argnums=(1, 2),  # k_cache, v_cache
        ), self)
        # Decode burst: K decode iterations fused into one compiled
        # program via lax.scan — sampled tokens feed back on device
        # and per-sequence budgets + stop sets are evaluated on device
        # too, so rows go inactive mid-burst without a host round-trip
        # (vLLM's --num-scheduler-steps analogue, but as a single XLA
        # program, and the window never collapses to 1 for
        # mixed-progress batches). One dispatch + one device_get per K
        # tokens; on a tunneled TPU (60 ms+ RTT per sync) this is the
        # difference between host-bound and device-bound serving.
        self._decode_burst_jit = InstrumentedJit("decode_burst", jax.jit(
            (self._decode_burst_deferred_impl if self._deferred
             else self._decode_burst_impl),
            static_argnames=("num_steps", "want_logprobs"),
            donate_argnums=(1, 2),  # k_cache, v_cache
        ), self)
        if self._sp_size > 1:
            from production_stack_tpu.parallel.context_serving import (
                sp_prefill_forward,
            )

            def _sp_step(params, k_cache, v_cache, tokens, page_table,
                         valid, last_index, temperature, top_p, top_k,
                         rng, lora, lora_ids, penalties, seeding,
                         bias, suppress, fsm, want_logprobs=False):
                row_logits, k_cache, v_cache = sp_prefill_forward(
                    params, self.config.model, tokens, page_table,
                    valid, last_index, k_cache, v_cache,
                    lora=lora, lora_ids=lora_ids,
                    mesh=self.mesh)
                raw_logits = row_logits
                if penalties is not None:
                    row_logits = apply_penalties(row_logits, *penalties)
                if bias is not None:
                    row_logits = row_logits + bias
                if suppress is not None:
                    row_logits = ModelRunner._apply_suppression(
                        row_logits, suppress)
                if fsm is not None:
                    row_logits = self._apply_guided_mask(
                        row_logits, fsm)
                seeds, seed_on, emitted = (
                    seeding if seeding is not None
                    else (None, None, None))
                sampled = sample_tokens(row_logits, temperature,
                                        top_p, top_k, rng,
                                        seeds=seeds, emitted=emitted,
                                        seed_mask=seed_on)
                if want_logprobs:
                    lp = token_logprobs(raw_logits, sampled,
                                        TOP_LOGPROBS_WIDTH)
                    return (sampled,) + lp, k_cache, v_cache
                return sampled, k_cache, v_cache

            self._sp_prefill_jit = InstrumentedJit(
                "sp_prefill",
                jax.jit(_sp_step, donate_argnums=(1, 2),
                        static_argnames=("want_logprobs",)),
                self)

        # Speculative verify (docs/speculative.md): ONE fixed-shape
        # program scores S = speculative_k + 1 positions per decode
        # slot through the T>1 (prefill) attention path over the page
        # table; the acceptance rule runs in-graph (spec_verify).
        self.spec_width = 0
        if config.scheduler.speculative_k > 0:
            # Composes with pp/cp: the verify program routes through
            # self._forward, which the pp wiring above already swapped
            # for the staged pipeline body (same signature), and the
            # cp wrapper below shards the verify span's W axis.
            self.spec_width = config.scheduler.speculative_k + 1
            # The Pallas prefill kernel may not lower at the thin
            # (decode_width, S) verify shape (Mosaic tiling rules are
            # shape-specific), so probe exactly that shape and degrade
            # ONLY the verify program to XLA attention — real prefill
            # keeps its measured-winner kernel.
            spec_model = model_config
            prefill_impl = (model_config.attention_impl_prefill
                            or model_config.attention_impl)
            if (prefill_impl.startswith("pallas")
                    and jax.default_backend() != "cpu"):
                err = (pallas_backend_error(config.cache.page_size)
                       or self._spec_lowering_error(
                           model_config, config))
                if err is not None:
                    logger.info(
                        "Speculative verify serves via XLA attention "
                        "(Pallas prefill failed lowering at the "
                        "verify shape): %s", err)
                    import copy
                    spec_model = copy.copy(model_config)
                    spec_model.attention_impl_prefill = "xla"
            self._spec_model = spec_model
            self.observatory.set_attention_impl(
                "spec_verify", spec_model.attention_impl_prefill
                or spec_model.attention_impl)
            self._spec_jit = InstrumentedJit("spec_verify", jax.jit(
                self._spec_verify_impl,
                static_argnames=("want_logprobs",),
                donate_argnums=(1, 2),  # k_cache, v_cache
            ), self)

        # Unified ragged step (docs/unified_step.md): ONE jitted
        # program serves genuinely mixed batches — decode/draft rows
        # and prefill chunk rows share a fixed [R, W] token block
        # (R and W each snap to closed bucket sets: W from the
        # prefill buckets, R from a doubling row lattice capped at
        # decode_width + prefill_width), sampled through the verify
        # rule so every row kind emits 1..span tokens through one
        # shape. Row bucketing keeps a lightly mixed step (the common
        # case: a few decode rows plus one chunk) from paying full-
        # width compute for pad rows. Pure-decode and pure-prefill
        # steps keep the bimodal dispatch paths, so greedy streams
        # stay byte-identical when no mixing happens.
        self.unified_span = max(self.spec_width, 1)
        self.unified_rows = self.decode_width + self.prefill_width
        buckets, b = [], 2
        while b < self.unified_rows:
            buckets.append(b)
            if b + b // 2 < self.unified_rows:
                buckets.append(b + b // 2)
            b *= 2
        buckets.append(self.unified_rows)
        self.unified_row_buckets = buckets
        # Last dispatched ragged shape, for occupancy metrics.
        self.last_unified_rows = 0
        self._unified = bool(config.scheduler.unified_step)
        if self._unified:
            # Composes with pp (the ragged [R, W] block rides the
            # staged forward — rows become microbatches, the per-row
            # descriptor triple threads through each ppermute handoff)
            # and with cp (the sp wrapper shards the W axis) —
            # unified_step_eligible dropped both disqualifiers.
            # Resolve the unified step's own attention impl: the
            # fused ragged kernel when it lowers AND is the measured
            # winner, else the composed prefill kernel (probed at the
            # [R, W] shapes the per-bucket probe never saw), else XLA
            # — degrading ONLY the ragged program, never real prefill
            # (the _spec_model pattern).
            unified_model, resolved = self._resolve_unified_impl(
                getattr(self, "_spec_model", model_config), config,
                auto_impl)
            self._unified_model = unified_model
            logger.info("Unified step attention impl: %s", resolved)
            self.observatory.set_attention_impl("unified", resolved)
            self._unified_jit = InstrumentedJit("unified", jax.jit(
                self._unified_impl,
                static_argnames=("want_logprobs",),
                donate_argnums=(1, 2),  # k_cache, v_cache
            ), self)

    def _record_timing(self, kind: str, t: int, wall: float) -> None:
        """PSTPU_TIMING walls: keep the log line, and fold the same
        wall into the observatory's dispatch ledger so
        ``GET /debug/compiles`` carries per-kind timing aggregates."""
        _timing_log(kind, t, wall)
        obs = self.observatory
        if obs is not None:
            obs.on_timing(kind, wall)

    def _probe_cache_struct(self, model_config, config):
        """Shared probe boilerplate: the exact serving cache struct
        (per_layer slice vs stacked + SMEM layer scalar, QuantKV when
        kv int8) and the shape scalars every lowering probe needs.
        Returns ``(nh, d, dtype, max_pages, cache, layer0)``."""
        nh, nkv, d = (model_config.num_attention_heads,
                      model_config.num_key_value_heads,
                      model_config.head_dim)
        dtype = model_config.jax_dtype
        max_pages = config.scheduler.max_pages_per_seq(
            config.cache.page_size)
        if config.cache.cache_layout == "per_layer":
            cache_shape = (nkv, config.cache.num_pages, d,
                           config.cache.page_size)
            layer0 = None
        else:
            cache_shape = (model_config.num_hidden_layers, nkv,
                           config.cache.num_pages, d,
                           config.cache.page_size)
            layer0 = jax.ShapeDtypeStruct((), np.int32)
        cache = (quant_cache_struct(cache_shape) if self.kv_quantized
                 else jax.ShapeDtypeStruct(cache_shape, dtype))
        return nh, d, dtype, max_pages, cache, layer0

    def _spec_lowering_error(self, model_config,
                             config) -> Optional[str]:
        """Probe the Pallas prefill kernel at the verify shape."""
        from production_stack_tpu.ops.prefill_attention_pallas import (
            paged_prefill_attention,
        )
        nh, d, dtype, max_pages, cache, layer0 = \
            self._probe_cache_struct(model_config, config)
        b, s = self.decode_width, self.spec_width
        return self._lowering_error(
            paged_prefill_attention,
            jax.ShapeDtypeStruct((b, s, nh, d), dtype), cache, cache,
            jax.ShapeDtypeStruct((b, max_pages), np.int32),
            jax.ShapeDtypeStruct((b, s), np.int32),
            jax.ShapeDtypeStruct((b,), np.int32), layer0)

    def _unified_widths(self) -> List[int]:
        """Every query width the mixed planner can emit."""
        return sorted({max(w, self.unified_span)
                       for w in self._buckets})

    def _unified_lowering_error(self, model_config,
                                config) -> Optional[str]:
        """Probe the Pallas prefill kernel at the ragged-step shapes
        ([unified_rows, W] for every W the mixed planner can emit;
        the smaller row buckets are strict sub-shapes and are taken
        to lower whenever the widest one does)."""
        from production_stack_tpu.ops.prefill_attention_pallas import (
            paged_prefill_attention,
        )
        nh, d, dtype, max_pages, cache, layer0 = \
            self._probe_cache_struct(model_config, config)
        r = self.unified_rows
        for w in self._unified_widths():
            err = self._lowering_error(
                paged_prefill_attention,
                jax.ShapeDtypeStruct((r, w, nh, d), dtype), cache,
                cache,
                jax.ShapeDtypeStruct((r, max_pages), np.int32),
                jax.ShapeDtypeStruct((r, w), np.int32),
                jax.ShapeDtypeStruct((r,), np.int32), layer0)
            if err is not None:
                return err
        return None

    def _ragged_lowering_error(self, model_config,
                               config) -> Optional[str]:
        """Probe the fused ragged kernel over the same [R, W] matrix
        as _unified_lowering_error, with the three-int descriptor
        operands (kv_lens, last_index, draft_lens) in place of the
        [R, W] positions the composed path takes."""
        from production_stack_tpu.ops.ragged_attention_pallas import (
            paged_ragged_attention,
        )
        nh, d, dtype, max_pages, cache, layer0 = \
            self._probe_cache_struct(model_config, config)
        r = self.unified_rows
        rows_i32 = jax.ShapeDtypeStruct((r,), np.int32)
        for w in self._unified_widths():
            err = self._lowering_error(
                paged_ragged_attention,
                jax.ShapeDtypeStruct((r, w, nh, d), dtype), cache,
                cache,
                jax.ShapeDtypeStruct((r, max_pages), np.int32),
                rows_i32, rows_i32, rows_i32, layer0)
            if err is not None:
                return err
        return None

    @staticmethod
    def _ragged_microbench_verdict() -> Optional[bool]:
        """Measured-winner verdict for the fused ragged kernel.

        Reads the ragged-suite rows (kind == 'ragged') of
        benchmarks/results/kernel_microbench.json: True when every
        measured cell wins (speedup >= 1.0), False when any loses,
        None when the file or the suite is absent — under 'auto' an
        absent measurement composes the prefill kernel rather than
        serving an unmeasured one (round-3's mistake was serving
        whatever merely compiled).
        """
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "..", "..",
            "benchmarks", "results", "kernel_microbench.json")
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return None
        if data.get("backend") != "tpu":
            return None
        rows = [row for row in data.get("rows", [])
                if row.get("kind") == "ragged"]
        if not rows:
            return None
        return all(float(row.get("speedup", 0.0)) >= 1.0
                   for row in rows)

    def _resolve_unified_impl(self, base_model, config,
                              auto_impl: bool):
        """Resolve the attention impl serving the unified [R, W] step.

        Returns ``(model, resolved)``: ``model`` is ``base_model`` or
        a shallow copy with ``attention_impl_prefill`` rewritten (the
        unified program dispatches through the T>1 path), ``resolved``
        the impl string for the observatory one-hot and bench extras.

        The ladder, top rung first:
          1. an explicit ``attention_impl_unified`` (probed and
             degraded on real TPU; served verbatim in interpret/CPU
             testing — that pin is how tier-1 holds byte-parity),
          2. the fused ragged kernel (pallas_ragged) when the family
             prefill impl is Pallas on TPU, it lowers at every ragged
             shape, AND — under 'auto' — the kernel microbench table
             records a measured win (an explicit family-wide 'pallas'
             skips the table as an operator override),
          3. the composed prefill kernel when IT lowers at the ragged
             shapes (the pre-fusion path),
          4. XLA attention.
        """
        import copy

        def with_impl(impl):
            if ((base_model.attention_impl_prefill
                 or base_model.attention_impl) == impl):
                return base_model, impl
            model = copy.copy(base_model)
            model.attention_impl_prefill = impl
            return model, impl

        explicit = base_model.attention_impl_unified
        if explicit:
            if (explicit.startswith("pallas")
                    and not explicit.endswith("-interpret")
                    and jax.default_backend() != "cpu"):
                err = pallas_backend_error(config.cache.page_size)
                if err is None:
                    probe = (self._ragged_lowering_error
                             if explicit.startswith("pallas_ragged")
                             else self._unified_lowering_error)
                    err = probe(base_model, config)
                if err is not None:
                    logger.error(
                        "attention_impl_unified=%s failed its "
                        "lowering probe; serving via XLA attention: "
                        "%s", explicit, err)
                    return with_impl("xla")
            return with_impl(explicit)

        prefill_impl = (base_model.attention_impl_prefill
                        or base_model.attention_impl)
        if (not prefill_impl.startswith("pallas")
                or jax.default_backend() == "cpu"):
            # XLA family (or CPU testing): compose it unchanged.
            return base_model, prefill_impl
        berr = pallas_backend_error(config.cache.page_size)
        if berr is not None:
            # Family resolution already degraded on this rule; the
            # unified site re-checks the ONE shared predicate so the
            # backend rule cannot drift across sites.
            logger.error("%s; unified step serves via XLA attention",
                         berr)
            return with_impl("xla")
        ragged_err = self._ragged_lowering_error(base_model, config)
        if ragged_err is None:
            if not auto_impl:
                # Explicit family-wide 'pallas': operator override,
                # the microbench table is not consulted.
                return with_impl("pallas_ragged")
            verdict = self._ragged_microbench_verdict()
            if verdict is True:
                return with_impl("pallas_ragged")
            if verdict is None:
                logger.info(
                    "Fused ragged kernel lowers but has no measured "
                    "rows in kernel_microbench.json — composing the "
                    "prefill kernel; run benchmarks/"
                    "kernel_microbench.py (ragged suite) on this "
                    "device to qualify it for 'auto'")
            else:
                logger.info(
                    "Fused ragged kernel lowers but loses the "
                    "measured microbench at serving shapes; "
                    "composing the prefill kernel")
        else:
            logger.info(
                "Fused ragged kernel failed TPU lowering (composing "
                "the prefill kernel): %s", ragged_err)
        err = self._unified_lowering_error(base_model, config)
        if err is not None:
            logger.info(
                "Unified ragged step serves via XLA attention "
                "(Pallas prefill failed lowering at a ragged "
                "shape): %s", err)
            return with_impl("xla")
        return base_model, prefill_impl

    @staticmethod
    def _lowering_error(fn, *args) -> Optional[str]:
        try:
            jax.jit(fn).trace(*args).lower(
                lowering_platforms=("tpu",))
            return None
        except Exception as e:  # noqa: BLE001 — any lowering failure
            return repr(e)[:400]

    def _resolve_pallas_impls(self, model_config, config,
                              empirical: bool = False) -> None:
        """Probe each Pallas kernel's TPU lowering at serving shapes.

        With ``empirical=True`` (attention_impl='auto'), a kernel that
        lowers must ALSO be the measured winner at the engine's shapes
        to be served (benchmarks/results/kernel_microbench.json, TPU
        v5e, 2026-07-31 post-aliasing-fix): the prefill kernel wins
        1.25-2.3x at every cell, but the decode kernel loses every
        serving cell (0.42-0.65x at ctx 2k-16k) — it is retired from
        'auto' entirely (PALLAS_DECODE_IN_AUTO). Serving the slower
        impl because it merely compiles was round-3's mistake
        (VERDICT r3 §missing 2).
        """
        nh, nkv, d = (model_config.num_attention_heads,
                      model_config.num_key_value_heads,
                      model_config.head_dim)
        dtype = model_config.jax_dtype
        max_pages = config.scheduler.max_pages_per_seq(
            config.cache.page_size)
        # Probe the exact serving form. Stacked layout: the full
        # stacked cache with a dynamic layer index (models pass layer
        # through SMEM prefetch). Per-layer layout: one layer's buffer
        # with no layer operand.
        if config.cache.cache_layout == "per_layer":
            cache_shape = (nkv, config.cache.num_pages, d,
                           config.cache.page_size)
            layer0 = None
        else:
            cache_shape = (model_config.num_hidden_layers, nkv,
                           config.cache.num_pages, d,
                           config.cache.page_size)
            layer0 = jax.ShapeDtypeStruct((), np.int32)
        cache = (quant_cache_struct(cache_shape) if self.kv_quantized
                 else jax.ShapeDtypeStruct(cache_shape, dtype))

        berr = pallas_backend_error(config.cache.page_size)
        if berr is not None:
            # Shared backend rule (pallas_backend_error): the lowering
            # probes below cannot see it, so gate explicitly here and
            # at the spec/unified resolution sites.
            logger.error("%s; serving via XLA attention", berr)
            model_config.attention_impl_decode = "xla"
            model_config.attention_impl_prefill = "xla"
            return

        from production_stack_tpu.ops.paged_attention_pallas import (
            paged_decode_attention,
        )
        from production_stack_tpu.ops.prefill_attention_pallas import (
            paged_prefill_attention,
        )
        b = config.scheduler.max_num_seqs
        pb = config.scheduler.prefill_batch_size
        probes = {
            "decode": [(
                paged_decode_attention,
                (jax.ShapeDtypeStruct((b, nh, d), dtype), cache, cache,
                 jax.ShapeDtypeStruct((b, max_pages), np.int32),
                 jax.ShapeDtypeStruct((b,), np.int32), layer0),
            )],
            # Serving compiles one prefill program per bucket — probe
            # them all, not just the widest (a Mosaic rule can fail at
            # one bucket shape only).
            "prefill": [(
                paged_prefill_attention,
                (jax.ShapeDtypeStruct((pb, t, nh, d), dtype), cache,
                 cache,
                 jax.ShapeDtypeStruct((pb, max_pages), np.int32),
                 jax.ShapeDtypeStruct((pb, t), np.int32),
                 jax.ShapeDtypeStruct((pb,), np.int32), layer0),
            ) for t in prefill_buckets(
                config.scheduler.prefill_chunk_size)],
        }
        for name, cases in probes.items():
            if (empirical and name == "decode"
                    and not PALLAS_DECODE_IN_AUTO):
                # Retired from 'auto' by the post-aliasing-fix
                # microbench (XLA decode 1.5-2.4x faster at every
                # serving shape — see PALLAS_DECODE_IN_AUTO): skip
                # the lowering probe too, so startup neither burns a
                # trace nor logs a lowering error for a path that
                # was never going to serve.
                model_config.attention_impl_decode = "xla"
                logger.info(
                    "Decode attention: XLA (measured winner at all "
                    "serving shapes; Pallas decode retired from "
                    "'auto' — kernel_microbench.json 2026-07-31)")
                continue
            err = next(
                (e for fn, shapes in cases
                 for e in [self._lowering_error(fn, *shapes)]
                 if e is not None), None)
            impl = "pallas" if err is None else "xla"
            if err:
                logger.error(
                    "Pallas %s kernel failed TPU lowering; this shape "
                    "serves via XLA attention: %s", name.upper(), err)
            setattr(model_config, f"attention_impl_{name}", impl)

    def set_guided_tables(self, fsm) -> None:
        """Device copies of the guided-decoding automaton tables
        (engine/guided.py). Uploaded once at engine init; the
        sampling steps gather mask[state] rows and advance
        state = transition[state, token] inside the compiled
        program (burst carry), so constrained rows run at full
        burst speed."""
        self._guided_trans = jnp.asarray(fsm.transition)
        self._guided_mask = jnp.asarray(fsm.mask)

    @property
    def _lora_stack(self):
        return (None if self.lora_registry is None
                else self.lora_registry.stack)

    # ---- compiled step ----------------------------------------------------

    def _step_impl(self, params, k_cache, v_cache, tokens, positions,
                   page_table, kv_lens, valid, last_index, temperature,
                   top_p, top_k, rng, lora, lora_ids, penalties,
                   seeding, bias, suppress, fsm,
                   sample_index_mode: str,
                   want_logprobs: bool = False):
        # Deliberate two-shape specialization ([B] decode feed-forward
        # vs [B, T] prefill/burst): exactly two traces, cached for the
        # process lifetime — not a per-step retrace.
        if tokens.ndim == 1:  # lint: allow-tracer-hygiene
            # Single-step decode feeds [B] tokens so the async
            # pipeline can consume the previous step's [B] sampled
            # array verbatim — zero eager ops on the feed-forward.
            # The reshape happens here, inside the traced program.
            tokens = tokens[:, None]
            positions = positions.reshape(tokens.shape)
            valid = valid.reshape(tokens.shape)
        logits, k_cache, v_cache = self._forward(
            params, self.config.model, tokens, positions, page_table,
            kv_lens, valid, k_cache, v_cache,
            lora=lora, lora_ids=lora_ids,
        )
        if sample_index_mode == "last":
            # Prefill: sample only from the final prompt position.
            row_logits = logits[jnp.arange(tokens.shape[0]), last_index]
        else:
            # Decode: T == 1.
            row_logits = logits[:, 0, :]
        raw_logits = row_logits
        if penalties is not None:
            # (counts, prompt_mask, presence, frequency, repetition);
            # None in the common no-penalty case so that path compiles
            # with zero penalty overhead.
            row_logits = apply_penalties(row_logits, *penalties)
        if bias is not None:
            # OpenAI logit_bias (dense [B, vocab], zero where unused);
            # after penalties, before sampling; logprobs stay raw.
            row_logits = row_logits + bias
        if suppress is not None:
            # min_tokens: stops cannot be generated while under the
            # row's minimum (vLLM semantics; logprobs stay raw).
            row_logits = self._apply_suppression(row_logits, suppress)
        if fsm is not None:
            # Guided decoding: the automaton masks last (the
            # grammar wins); host advances the state (one token
            # per dispatch on this path).
            row_logits = self._apply_guided_mask(row_logits, fsm)
        seeds, seed_on, emitted = (
            seeding if seeding is not None else (None, None, None))
        sampled = sample_tokens(row_logits, temperature, top_p, top_k,
                                rng, seeds=seeds, emitted=emitted,
                                seed_mask=seed_on)
        if want_logprobs:
            # From the raw distribution (pre-penalty/temperature), the
            # OpenAI logprobs contract. raw_logits is bound before the
            # penalty rewrite above.
            lp = token_logprobs(raw_logits, sampled,
                                TOP_LOGPROBS_WIDTH)
            return (sampled,) + lp, k_cache, v_cache
        return sampled, k_cache, v_cache

    def _decode_burst_impl(self, params, k_cache, v_cache, tokens,
                           positions, page_table, kv_lens, active,
                           budgets, stop_tokens, temperature, top_p,
                           top_k, rng, lora, lora_ids, penalties,
                           seeding, bias, suppress, fsm,
                           num_steps: int,
                           want_logprobs: bool = False):
        """K chained decode iterations in one program, with per-row
        lifecycle on device.

        Carry = (last tokens [B,1], positions [B,1], kv_lens [B],
        active [B], emitted [B], caches); each iteration writes KV for
        the active rows (``valid`` mask redirects inactive rows to the
        trash page), attends, samples, checks each row's stop set and
        token budget, and feeds the sampled token into the next — no
        host round-trip between tokens, and a row that finishes early
        simply freezes (its slots emit -1) instead of forcing the
        whole batch back to single-step.

        Args (beyond the single-step set):
          active:      [B] bool — rows that decode this burst
          budgets:     [B] int32 — max tokens this burst may emit per
                       row (min(K, max_tokens budget, model_len
                       budget) computed by the scheduler)
          stop_tokens: [B, S] int32 — per-row stop set, padded with -1

        Returns sampled tokens [K, B] (-1 for frozen slots); with
        ``want_logprobs`` a tuple ([K, B] tokens, [K, B] sampled
        logprobs, [K, B, W] top ids, [K, B, W] top logprobs).
        """
        b = active.shape[0]
        if penalties is not None:
            # (counts, prompt_mask, presence, frequency, repetition):
            # counts joins the scan carry (updated per step), the rest
            # stay loop-invariant closures.
            counts0, penalties = penalties[0], penalties[1:]
        else:
            # Zero-size placeholder keeps the carry structure uniform.
            counts0 = jnp.zeros((b, 0), jnp.int32)

        sample_step = self._burst_sample_step(
            b, penalties, seeding, bias, suppress, temperature,
            top_p, top_k, stop_tokens, budgets, want_logprobs)
        fsm0 = (jnp.zeros((0,), jnp.int32) if fsm is None else fsm)

        def body(carry, step_rng):
            tok, pos, kv, act, emitted, counts, fs, kc, vc = carry
            logits, kc, vc = self._forward(
                params, self.config.model, tok, pos, page_table,
                kv, act[:, None], kc, vc, lora=lora,
                lora_ids=lora_ids,
            )
            out, sampled, emitted, counts, act_next, fs = \
                sample_step(logits, step_rng, act, emitted, counts,
                            fs)
            step = act_next.astype(pos.dtype)
            return ((jnp.where(act, sampled, tok[:, 0])[:, None],
                     pos + step[:, None], kv + step, act_next,
                     emitted, counts, fs, kc, vc), out)

        rngs = jax.random.split(rng, num_steps)
        emitted0 = jnp.zeros(active.shape, jnp.int32)
        carry = (tokens, positions, kv_lens, active, emitted0,
                 counts0, fsm0, k_cache, v_cache)
        (_, _, _, _, _, _, _, k_cache, v_cache), out = jax.lax.scan(
            body, carry, rngs
        )
        return out, k_cache, v_cache

    def _burst_sample_step(self, b, penalties, seeding, bias,
                           suppress, temperature, top_p, top_k,
                           stop_tokens, budgets, want_logprobs):
        # ``fsm`` rides the burst carry: a zero-size placeholder
        # means unguided (compiled without the table gathers).
        """The burst bodies' shared logits -> (out, lifecycle) step:
        penalties, (seeded) sampling, logprobs, occurrence counts,
        stop/budget freeze. One definition so the eager and deferred
        KV-write bursts cannot drift apart in sampling semantics."""

        def sample_step(logits, step_rng, act, emitted, counts,
                        fsm):
            row_logits = logits[:, 0, :]
            raw_logits = row_logits
            if penalties is not None:
                prompt_mask, presence, frequency, repetition = penalties
                row_logits = apply_penalties(
                    row_logits, counts, prompt_mask, presence,
                    frequency, repetition)
            if bias is not None:
                # OpenAI logit_bias: after penalties, before sampling;
                # logprobs stay raw.
                row_logits = row_logits + bias
            if suppress is not None:
                # min_tokens: stops masked while under the minimum
                # (emitted counts this burst's tokens on top of the
                # payload-time remainder).
                row_logits = self._apply_suppression(
                    row_logits, suppress, emitted=emitted)
            if fsm.shape[0]:
                # Guided decoding: the automaton masks last.
                row_logits = self._apply_guided_mask(row_logits,
                                                     fsm)
            if seeding is not None:
                # Seeded rows' randomness depends only on (seed,
                # absolute emitted index), so reproducibility survives
                # burst boundaries and batch composition.
                seeds, seed_on, emitted_start = seeding
                sampled = sample_tokens(
                    row_logits, temperature, top_p, top_k, step_rng,
                    seeds=seeds, emitted=emitted_start + emitted,
                    seed_mask=seed_on)
            else:
                sampled = sample_tokens(
                    row_logits, temperature, top_p, top_k, step_rng
                )
            out = jnp.where(act, sampled, -1)
            if want_logprobs:
                out = (out,) + token_logprobs(raw_logits, sampled,
                                              TOP_LOGPROBS_WIDTH)
            emitted = emitted + act
            if penalties is not None:
                # Occurrence counts track the burst on device so later
                # steps penalize tokens sampled earlier in the burst.
                counts = counts.at[jnp.arange(b), sampled].add(
                    act.astype(counts.dtype))
            hit_stop = jnp.any(
                sampled[:, None] == stop_tokens, axis=-1
            )
            act_next = act & ~hit_stop & (emitted < budgets)
            if fsm.shape[0]:
                # Constrained rows can only have sampled an in-table
                # id (the mask forbids the rest); the clip keeps the
                # gather in-bounds for unconstrained rows, whose fsm
                # stays -1 via the where.
                width = self._guided_trans.shape[1]
                nxt = self._guided_trans[
                    jnp.clip(fsm, 0), jnp.clip(sampled, 0, width - 1)]
                fsm = jnp.where(act & (fsm >= 0), nxt, fsm)
            return out, sampled, emitted, counts, act_next, fsm

        return sample_step

    def _decode_burst_deferred_impl(self, params, k_cache, v_cache,
                                    tokens, positions, page_table,
                                    kv_lens, active, budgets,
                                    stop_tokens, temperature, top_p,
                                    top_k, rng, lora, lora_ids,
                                    penalties, seeding, bias,
                                    suppress, fsm, num_steps: int,
                                    want_logprobs: bool = False):
        """_decode_burst_impl with per-burst (not per-step) KV writes.

        Same contract and carry discipline, except: each step's K/V
        goes into dense per-layer tail buffers ([B, S, kv, d] one-hot
        selects — ops/attention.write_to_tail) and attention covers
        pages + tail positionally (paged_attention k_tail/v_tail);
        the paged caches stay READ-ONLY through the scan (loop
        invariants, not carry) and the tails flush to the pages with
        one write_to_pages per layer at burst end. The round-5
        on-chip ablation measured the per-step scatters at ~5.1 of
        11.1 ms for ~1 MB of writes (results/round5_notes.md).

        The pages hold exactly the pre-burst tokens throughout, so
        the frozen cached-token count is positions[:, 0] (the first
        burst token's absolute position) and tail slot s sits at
        absolute position kv_lens0 + s.
        """
        b = active.shape[0]
        m = self.config.model
        if penalties is not None:
            counts0, penalties = penalties[0], penalties[1:]
        else:
            counts0 = jnp.zeros((b, 0), jnp.int32)

        kv_lens0 = positions[:, 0]  # pages hold this many tokens
        tail_shape = (b, num_steps, m.num_key_value_heads, m.head_dim)
        dtype = m.jax_dtype
        k_tails0 = tuple(jnp.zeros(tail_shape, dtype)
                         for _ in range(m.num_hidden_layers))
        v_tails0 = tuple(jnp.zeros(tail_shape, dtype)
                         for _ in range(m.num_hidden_layers))

        sample_step = self._burst_sample_step(
            b, penalties, seeding, bias, suppress, temperature,
            top_p, top_k, stop_tokens, budgets, want_logprobs)
        fsm0 = (jnp.zeros((0,), jnp.int32) if fsm is None else fsm)

        def body(carry, step_rng):
            tok, pos, act, emitted, counts, fs, kt, vt = carry
            logits, kt, vt = self._forward(
                params, m, tok, pos, page_table, kv_lens0,
                act[:, None], k_cache, v_cache, lora=lora,
                lora_ids=lora_ids, kv_tail=(kt, vt),
            )
            out, sampled, emitted, counts, act_next, fs = \
                sample_step(logits, step_rng, act, emitted, counts,
                            fs)
            step = act_next.astype(pos.dtype)
            return ((jnp.where(act, sampled, tok[:, 0])[:, None],
                     pos + step[:, None], act_next, emitted, counts,
                     fs, kt, vt), out)

        rngs = jax.random.split(rng, num_steps)
        emitted0 = jnp.zeros(active.shape, jnp.int32)
        carry = (tokens, positions, active, emitted0, counts0, fsm0,
                 k_tails0, v_tails0)
        (_, _, _, emitted, _, _, k_tails, v_tails), out = jax.lax.scan(
            body, carry, rngs
        )

        # Flush: one batched scatter per layer for the whole burst.
        tail_pos = kv_lens0[:, None] + jnp.arange(num_steps)[None, :]
        tail_valid = (jnp.arange(num_steps)[None, :]
                      < emitted[:, None])
        if isinstance(k_cache, tuple):
            k_cache = tuple(
                write_to_pages(c, k_tails[l], page_table, tail_pos,
                               tail_valid)
                for l, c in enumerate(k_cache))
            v_cache = tuple(
                write_to_pages(c, v_tails[l], page_table, tail_pos,
                               tail_valid)
                for l, c in enumerate(v_cache))
        else:
            for l in range(m.num_hidden_layers):
                k_cache = write_to_pages(k_cache, k_tails[l],
                                         page_table, tail_pos,
                                         tail_valid, layer=l)
                v_cache = write_to_pages(v_cache, v_tails[l],
                                         page_table, tail_pos,
                                         tail_valid, layer=l)
        return out, k_cache, v_cache

    def _spec_verify_impl(self, params, k_cache, v_cache, tokens,
                          positions, page_table, kv_lens, valid,
                          drafts, draft_lens, temperature, top_p,
                          top_k, rng, lora, lora_ids,
                          want_logprobs: bool = False):
        """One fixed-shape speculative verify step.

        ``tokens[i] = [last_committed, d_1 .. d_k]`` (padded) at
        absolute positions total_len-1 .. total_len-1+k. The forward
        writes the draft tokens' KV into the sequence's pages exactly
        like a prefill chunk (invalid slots land in the trash page)
        and attends causally, so ``logits[i, j]`` is the target
        model's distribution for the token at offset j past the
        committed length — all k+1 positions scored in ONE pass.

        Rejected drafts need NO device rollback: their KV lives past
        the committed length in private pages (prefix hashing only
        ever covers prompt tokens — scheduler.on_prefill_executed),
        causally invisible to every later query until the next step
        overwrites those positions (docs/speculative.md §rollback).
        """
        logits, k_cache, v_cache = self._forward(
            params, self._spec_model, tokens, positions, page_table,
            kv_lens, valid, k_cache, v_cache,
            lora=lora, lora_ids=lora_ids,
        )
        out = spec_verify(logits, drafts, draft_lens, temperature,
                          top_p, top_k, rng)
        if want_logprobs:
            # OpenAI logprobs from the raw per-position distributions;
            # positions past a row's emitted count are discarded by
            # the host parse.
            b, s, v = logits.shape
            lp = token_logprobs(logits.reshape(b * s, v),
                                jnp.clip(out, 0).reshape(b * s),
                                TOP_LOGPROBS_WIDTH)
            lp = tuple(x.reshape((b, s) + x.shape[1:]) for x in lp)
            return (out,) + lp, k_cache, v_cache
        return out, k_cache, v_cache

    def _unified_impl(self, params, k_cache, v_cache, tokens,
                      positions, page_table, kv_lens, valid,
                      last_index, drafts, draft_lens, temperature,
                      top_p, top_k, rng, lora, lora_ids,
                      want_logprobs: bool = False):
        """One fixed-shape ragged step (docs/unified_step.md).

        ``tokens`` is the [R, W] ragged block: a decode/draft row
        occupies its leading 1 + draft_len slots exactly like a
        verify row ([last_committed, d_1..d_k] at positions
        total_len-1 ..), a prefill chunk row occupies up to W slots
        of prompt tokens, and pad slots are masked by ``valid`` (KV
        lands in the trash page). The forward is the T>1
        chunked-prefill attention path unchanged — its contract
        (per-row contiguous positions, causal mask against the
        row's cached context) already covers mixed query lengths
        against the page table.

        Sampling unifies through the verify rule: the span gather
        ``span[i, j] = logits[i, last_index_i - draft_lens_i + j]``
        collects each row's scoring span (a draft row's span starts
        at its committed token; for draft-free rows the span IS the
        last real position, draft_lens 0), and spec_verify emits
        1..span tokens per row through ONE shape — a draft-free
        greedy row degenerates to the plain argmax, bit-identical
        to sample_tokens at temperature 0.
        """
        logits, k_cache, v_cache = self._forward(
            params, self._unified_model, tokens, positions,
            page_table, kv_lens, valid, k_cache, v_cache,
            lora=lora, lora_ids=lora_ids,
        )
        s = drafts.shape[-1] + 1
        start = jnp.clip(last_index - draft_lens, 0)
        idx = jnp.clip(start[:, None] + jnp.arange(s)[None, :], 0,
                       tokens.shape[1] - 1)
        span = jnp.take_along_axis(logits, idx[:, :, None], axis=1)
        out = spec_verify(span, drafts, draft_lens, temperature,
                          top_p, top_k, rng)
        if want_logprobs:
            # Raw per-span-position distributions (the OpenAI
            # contract); positions past a row's emitted count are
            # discarded by the host parse.
            b, _, v = span.shape
            lp = token_logprobs(span.reshape(b * s, v),
                                jnp.clip(out, 0).reshape(b * s),
                                TOP_LOGPROBS_WIDTH)
            lp = tuple(x.reshape((b, s) + x.shape[1:]) for x in lp)
            return (out,) + lp, k_cache, v_cache
        return out, k_cache, v_cache

    def _next_rng(self) -> jax.Array:
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _bucket_for(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        return self._buckets[-1]

    def _row_bucket_for(self, n: int) -> int:
        for b in self.unified_row_buckets:
            if n <= b:
                return b
        return self.unified_row_buckets[-1]

    # ---- payload execution (shared by host 0 and multihost workers) -------

    def execute_payload(self, kind: int, payload: dict,
                        t: int = 1) -> jax.Array:
        """Run one compiled step from a numpy payload.

        The payload is the complete device-program input (including the
        rng key), so host 0 and multihost workers — which receive it
        over the MultihostStepBridge broadcast — dispatch bit-identical
        programs (parallel/distributed.py). For decode (kind 2), ``t``
        is the multi-step window; prefill uses it as the token bucket
        (already baked into the array shapes).
        """
        from production_stack_tpu.parallel.distributed import (
            KIND_EMBED,
            KIND_SPEC,
            KIND_UNIFIED,
        )
        if kind == KIND_EMBED:
            return self.embedder.run_chunk(payload["tokens"],
                                           payload["lengths"])
        lora_ids = payload.get("lora_ids")
        lora_ids = (None if lora_ids is None
                    else _as_device(lora_ids))
        penalties, seeding, bias, suppress, fsm = \
            self._optional_device_inputs(payload)
        want_lp = bool(payload.get("want_logprobs", False))
        if kind == KIND_SPEC:
            # Speculative verify: the scheduler only plans eligible
            # rows (no penalties/seeds/bias/min_tokens/guided), so
            # the program compiles without those inputs.
            sampled, self.k_cache, self.v_cache = self._spec_jit(
                self.params, self.k_cache, self.v_cache,
                _as_device(payload["tokens"]),
                _as_device(payload["positions"]),
                _as_device(payload["page_table"]),
                _as_device(payload["kv_lens"]),
                _as_device(payload["valid"]),
                _as_device(payload["drafts"]),
                _as_device(payload["draft_lens"]),
                _as_device(payload["temperature"]),
                _as_device(payload["top_p"]),
                _as_device(payload["top_k"]),
                _as_device(payload["rng"]),
                self._lora_stack, lora_ids,
                want_logprobs=want_lp,
            )
            return sampled  # [B, S] (+ logprob arrays when requested)
        if kind == KIND_UNIFIED:
            # Mixed ragged step: the scheduler only plans eligible
            # rows (no penalties/seeds/bias/min_tokens/guided — the
            # spec-row exclusion set), so the program compiles
            # without those inputs.
            sampled, self.k_cache, self.v_cache = self._unified_jit(
                self.params, self.k_cache, self.v_cache,
                _as_device(payload["tokens"]),
                _as_device(payload["positions"]),
                _as_device(payload["page_table"]),
                _as_device(payload["kv_lens"]),
                _as_device(payload["valid"]),
                _as_device(payload["last_index"]),
                _as_device(payload["drafts"]),
                _as_device(payload["draft_lens"]),
                _as_device(payload["temperature"]),
                _as_device(payload["top_p"]),
                _as_device(payload["top_k"]),
                _as_device(payload["rng"]),
                self._lora_stack, lora_ids,
                want_logprobs=want_lp,
            )
            return sampled  # [R, span] (+ logprobs when requested)
        if kind == 2 and t > 1:
            sampled, self.k_cache, self.v_cache = \
                self._decode_burst_jit(
                    self.params, self.k_cache, self.v_cache,
                    _as_device(payload["tokens"]),
                    _as_device(payload["positions"]),
                    _as_device(payload["page_table"]),
                    _as_device(payload["kv_lens"]),
                    _as_device(payload["active"]),
                    _as_device(payload["budgets"]),
                    _as_device(payload["stop_tokens"]),
                    _as_device(payload["temperature"]),
                    _as_device(payload["top_p"]),
                    _as_device(payload["top_k"]),
                    _as_device(payload["rng"]),
                    self._lora_stack, lora_ids, penalties, seeding,
                    bias, suppress, fsm,
                    num_steps=t, want_logprobs=want_lp,
                )
            return sampled  # [K, B] (+ logprob arrays when requested)
        sampled, self.k_cache, self.v_cache = self._step_jit(
            self.params, self.k_cache, self.v_cache,
            _as_device(payload["tokens"]),
            _as_device(payload["positions"]),
            _as_device(payload["page_table"]),
            _as_device(payload["kv_lens"]),
            _as_device(payload["valid"]),
            _as_device(payload["last_index"]),
            _as_device(payload["temperature"]),
            _as_device(payload["top_p"]),
            _as_device(payload["top_k"]),
            _as_device(payload["rng"]),
            self._lora_stack, lora_ids, penalties, seeding, bias,
            suppress, fsm,
            sample_index_mode=("last" if kind == 1 else "first"),
            want_logprobs=want_lp,
        )
        return sampled

    @staticmethod
    def _lp_entry(seq, slp, tids, tlps):
        """One position's logprob info, trimmed to the row's request."""
        k = min(max(seq.sampling.top_logprobs, 0), TOP_LOGPROBS_WIDTH)
        return (float(slp),
                [(int(tids[j]), float(tlps[j])) for j in range(k)])

    def _penalty_payload(self, seqs: "List[Optional[Sequence]]",
                         pad_to: int) -> dict:
        """Per-row penalty inputs, or {} when no row needs them (the
        no-penalty batch keeps its penalty-free compiled program and
        pays no [B, vocab] host->device transfer). ``None`` rows
        (e.g. mid-prompt prefill chunks that discard their sample)
        keep the no-op defaults."""
        if not any(s is not None and s.sampling.needs_penalties
                   for s in seqs):
            return {}
        v = self.config.model.vocab_size
        counts = np.zeros((pad_to, v), np.int32)
        pmask = np.zeros((pad_to, v), bool)
        presence = np.zeros((pad_to,), np.float32)
        frequency = np.zeros((pad_to,), np.float32)
        repetition = np.ones((pad_to,), np.float32)
        for i, seq in enumerate(seqs):
            if seq is None:
                continue
            sp = seq.sampling
            presence[i] = sp.presence_penalty
            frequency[i] = sp.frequency_penalty
            repetition[i] = sp.repetition_penalty
            if sp.needs_penalties:
                # Both asarray calls index host Python lists, not
                # device arrays — the host-read lint proves this
                # flow-sensitively (no waiver needed).
                if seq.output_token_ids:
                    np.add.at(
                        counts[i],
                        np.asarray(seq.output_token_ids,
                                   np.int64), 1)
                pmask[i, np.asarray(
                    seq.prompt_token_ids, np.int64)] = True
        return {"pen_counts": counts, "pen_prompt_mask": pmask,
                "pen_presence": presence, "pen_frequency": frequency,
                "pen_repetition": repetition}

    def _seed_payload(self, seqs: "List[Optional[Sequence]]",
                      pad_to: int) -> dict:
        """Per-row seed inputs, or {} when no row set a seed (the
        unseeded batch keeps its seed-free compiled program)."""
        if not any(s is not None and s.sampling.seed is not None
                   for s in seqs):
            return {}
        seeds = np.zeros((pad_to,), np.uint32)
        seed_on = np.zeros((pad_to,), bool)
        emitted = np.zeros((pad_to,), np.int32)
        for i, seq in enumerate(seqs):
            if seq is None:
                continue
            if seq.sampling.seed is not None:
                # Full 32-bit seed; seededness rides the separate
                # ``seed_on`` mask so no seed bit is sacrificed to
                # gating (a 31-bit fold would collide distinct user
                # seeds, e.g. 1 and 0x80000001).
                seeds[i] = int(seq.sampling.seed) & 0xFFFFFFFF
                seed_on[i] = True
            emitted[i] = seq.num_generated
        return {"seed_rows": seeds.view(np.int32),
                "seed_on": seed_on,
                "seed_emitted": emitted}

    def _bias_payload(self, seqs: "List[Optional[Sequence]]",
                      pad_to: int) -> dict:
        """Per-row logit-bias matrix, or {} when no row uses one (the
        bias-free batch keeps its bias-free compiled program and pays
        no [B, vocab] host->device transfer).

        The matrix is constant while the batch's row composition is —
        cached by (row seq_id, bias identity) so the single-step path
        doesn't rebuild a [B, vocab] dense matrix per token (it still
        rides each dispatch's payload: the multihost broadcast needs
        the full input set — same trade the penalty mask makes)."""
        if not any(s is not None and s.sampling.logit_bias
                   for s in seqs):
            return {}
        key = (pad_to, tuple(
            (s.seq_id, tuple(sorted(s.sampling.logit_bias.items())))
            if s is not None and s.sampling.logit_bias else None
            for s in seqs))
        cached = getattr(self, "_bias_cache", None)
        if cached is not None and cached[0] == key:
            return {"logit_bias": cached[1]}
        v = self.config.model.vocab_size
        bias = np.zeros((pad_to, v), np.float32)
        for i, seq in enumerate(seqs):
            if seq is None or not seq.sampling.logit_bias:
                continue
            for tid, b in seq.sampling.logit_bias.items():
                # Out-of-vocab ids are rejected with a 400 at request
                # time when the serving vocab is known (server.py); the
                # guard here keeps direct-engine callers safe.
                if 0 <= int(tid) < v:
                    bias[i, int(tid)] = float(b)
        self._bias_cache = (key, bias)
        return {"logit_bias": bias}

    def _suppress_payload(self, seqs: "List[Optional[Sequence]]",
                          pad_to: int) -> dict:
        """min_tokens stop-suppression inputs, or {} when no row is
        under its minimum: per-row stop-set ids (EOS included —
        padded with -1 to STOP_SET_WIDTH) and the count of tokens the
        row must still emit before a stop may be GENERATED. The
        sampling steps mask those ids to -inf while under the
        minimum; ids beyond the fixed width are protected by the host
        finish guard (scheduler._append_token) instead."""
        if not any(s is not None
                   and s.sampling.min_tokens > s.num_generated
                   for s in seqs):
            return {}
        ids = np.full((pad_to, STOP_SET_WIDTH), -1, np.int32)
        rem = np.zeros((pad_to,), np.int32)
        for i, seq in enumerate(seqs):
            if seq is None:
                continue
            r = seq.sampling.min_tokens - seq.num_generated
            if r <= 0:
                continue
            rem[i] = r
            sids = seq.sampling.stop_token_ids[:STOP_SET_WIDTH]
            ids[i, :len(sids)] = sids
        return {"sup_ids": ids, "sup_rem": rem}

    @staticmethod
    def _apply_suppression(row_logits, suppress, emitted=None):
        """Mask suppressed token ids to -inf for rows still under
        their min_tokens. ``emitted`` (burst paths) counts tokens
        emitted THIS dispatch on top of the payload-time remainder;
        None (single-step/prefill: at most one token per dispatch)
        means the payload-time remainder is current."""
        ids, rem = suppress  # [B, W] (-1 padded), [B]
        b = row_logits.shape[0]
        under = (rem > 0) if emitted is None else (emitted < rem)
        pen = jnp.where((ids >= 0) & under[:, None], -1e30, 0.0)
        return row_logits.at[
            jnp.arange(b)[:, None], jnp.clip(ids, 0)].add(pen)

    def _guided_payload(self, seqs: "List[Optional[Sequence]]",
                        pad_to: int) -> dict:
        """Per-row automaton states ([B] int32, -1 = unconstrained),
        or {} when no row is guided (unguided batches keep their
        table-free compiled program)."""
        if not any(s is not None and s.fsm_state is not None
                   for s in seqs):
            return {}
        state = np.full((pad_to,), -1, np.int32)
        for i, seq in enumerate(seqs):
            if seq is not None and seq.fsm_state is not None:
                state[i] = seq.fsm_state
        return {"fsm_state": state}

    def _apply_guided_mask(self, row_logits, fsm):
        """-inf every token the automaton disallows from each
        constrained row's state (applied LAST — the grammar wins
        over bias and penalties). The tables stop at the byte+special
        width (guided.py TABLE_WIDTH); every id beyond it is
        inadmissible for constrained rows, so the gathered rows pad
        with False up to the vocab."""
        constrained = fsm >= 0
        st = jnp.clip(fsm, 0)
        allowed = self._guided_mask[st]  # [B, table_width] bool
        pad = row_logits.shape[-1] - allowed.shape[-1]
        if pad > 0:
            allowed = jnp.pad(allowed, ((0, 0), (0, pad)),
                              constant_values=False)
        return jnp.where(constrained[:, None] & ~allowed, -1e30,
                         row_logits)

    @staticmethod
    def _optional_device_inputs(payload: dict):
        """(penalties, seeding, bias, suppress, fsm) device inputs
        from a step payload; each is None when its keys are
        absent."""
        penalties = None
        if "pen_prompt_mask" in payload:
            penalties = (
                _as_device(payload["pen_counts"]),
                _as_device(payload["pen_prompt_mask"]),
                _as_device(payload["pen_presence"]),
                _as_device(payload["pen_frequency"]),
                _as_device(payload["pen_repetition"]),
            )
        seeding = None
        if "seed_rows" in payload:
            seeding = (_as_device(payload["seed_rows"]),
                       _as_device(payload["seed_on"]),
                       _as_device(payload["seed_emitted"]))
        bias = (_as_device(payload["logit_bias"])
                if "logit_bias" in payload else None)
        suppress = ((_as_device(payload["sup_ids"]),
                     _as_device(payload["sup_rem"]))
                    if "sup_ids" in payload else None)
        fsm = (_as_device(payload["fsm_state"])
               if "fsm_state" in payload else None)
        return penalties, seeding, bias, suppress, fsm

    def _dispatch(self, kind: int, t: int, payload: dict) -> jax.Array:
        if self.bridge is not None:
            # Atomic publish+execute: see MultihostStepBridge.lock.
            with self.bridge.lock:
                self.bridge.publish(kind, t, payload)
                return self.execute_payload(kind, payload, t)
        return self.execute_payload(kind, payload, t)

    # ---- prefill ----------------------------------------------------------

    def run_sp_prefill(self, plan: PrefillPlan):
        """Context-parallel whole-prompt prefill: ONE dispatch covers
        the entire prompt with the sequence sharded over 'sp'
        (parallel/context_serving.py). Returns the sampled first
        token."""
        if self.bridge is not None:
            raise NotImplementedError(
                "context-parallel prefill over the multihost step "
                "bridge")
        chunk = plan.chunks[0]
        seq = chunk.seq
        n = len(chunk.chunk_tokens)
        sp = self._sp_size
        # Pow2 T bucket, padded to an sp multiple, so the compiled
        # shape set stays small.
        t = 16
        while t < n:
            t *= 2
        t += (-t) % sp

        tokens = np.zeros((1, t), np.int32)
        valid = np.zeros((1, t), bool)
        tokens[0, :n] = chunk.chunk_tokens
        valid[0, :n] = True
        sp_params = seq.sampling
        opt = {}
        opt.update(self._penalty_payload([seq], 1))
        opt.update(self._seed_payload([seq], 1))
        opt.update(self._bias_payload([seq], 1))
        opt.update(self._suppress_payload([seq], 1))
        opt.update(self._guided_payload([seq], 1))
        penalties, seeding, bias, suppress, fsm = \
            self._optional_device_inputs(opt)
        want_lp = sp_params.logprobs
        lora_ids = (None if self.lora_registry is None
                    else jnp.asarray(
                        np.asarray([seq.lora_id], np.int32)))
        sampled, self.k_cache, self.v_cache = self._sp_prefill_jit(
            self.params, self.k_cache, self.v_cache,
            jnp.asarray(tokens),
            jnp.asarray(self._page_table_rows([seq])),
            jnp.asarray(valid),
            jnp.asarray(np.asarray([n - 1], np.int32)),
            jnp.asarray(np.asarray([sp_params.temperature],
                                   np.float32)),
            jnp.asarray(np.asarray([sp_params.top_p], np.float32)),
            jnp.asarray(np.asarray([sp_params.top_k], np.int32)),
            self._next_rng(), self._lora_stack, lora_ids,
            penalties, seeding, bias, suppress, fsm,
            want_logprobs=want_lp,
        )
        host = jax.device_get(sampled)
        if want_lp:
            toks, slp, tids, tlps = host
            return ([int(toks[0])],
                    [self._lp_entry(seq, slp[0], tids[0], tlps[0])])
        return [int(host[0])], None

    def run_prefill(self, plan: PrefillPlan
                    ) -> Tuple[List[Optional[int]], Optional[list]]:
        """Execute one batched prefill step (the next chunk of up to
        ``prefill_batch_size`` distinct sequences, rows padded to the
        fixed width). Returns (tokens, logprobs): one sampled token
        per chunk — None for rows whose prompt is not yet fully
        prefilled — and, when any sampling row requested logprobs, a
        parallel list of per-row logprob entries (else None)."""
        if plan.sp:
            return self.run_sp_prefill(plan)
        chunks = plan.chunks
        b = self.prefill_width
        t = self._bucket_for(max(len(c.chunk_tokens) for c in chunks))

        tokens = np.zeros((b, t), np.int32)
        positions = np.zeros((b, t), np.int32)
        valid = np.zeros((b, t), bool)
        kv_lens = np.zeros((b,), np.int32)
        last_index = np.zeros((b,), np.int32)
        # Pad rows stay temperature 0 (see run_decode).
        temperature = np.zeros((b,), np.float32)
        top_p = np.ones((b,), np.float32)
        top_k = np.zeros((b,), np.int32)

        for i, chunk in enumerate(chunks):
            n = len(chunk.chunk_tokens)
            tokens[i, :n] = chunk.chunk_tokens
            positions[i, :n] = np.arange(
                chunk.chunk_start, chunk.chunk_start + n
            )
            valid[i, :n] = True
            kv_lens[i] = chunk.chunk_start + n
            last_index[i] = n - 1
            sp = chunk.seq.sampling
            temperature[i] = sp.temperature
            top_p[i] = sp.top_p
            top_k[i] = sp.top_k

        payload = {
            "tokens": tokens,
            "positions": positions,
            "valid": valid,
            "page_table": self._page_table_rows(
                [c.seq for c in chunks], pad_to=b),
            "kv_lens": kv_lens,
            "last_index": last_index,
            "temperature": temperature,
            "top_p": top_p,
            "top_k": top_k,
            "rng": np.asarray(self._next_rng()),
        }
        if self.lora_registry is not None:
            ids = np.zeros((b,), np.int32)
            for i, chunk in enumerate(chunks):
                ids[i] = chunk.seq.lora_id
            payload["lora_ids"] = ids
        # Only rows whose LAST chunk is in this dispatch keep their
        # sampled token; mid-prompt chunks skip the [B, vocab] penalty
        # transfer and the penalized program entirely.
        sampling_rows = [c.seq if c.is_last_chunk else None
                         for c in chunks]
        payload.update(self._penalty_payload(sampling_rows, b))
        payload.update(self._seed_payload(sampling_rows, b))
        payload.update(self._bias_payload(sampling_rows, b))
        payload.update(self._suppress_payload(sampling_rows, b))
        payload.update(self._guided_payload(sampling_rows, b))
        want_lp = any(s is not None and s.sampling.logprobs
                      for s in sampling_rows)
        if want_lp:
            payload["want_logprobs"] = True

        t0 = time.perf_counter() if _TIMING else 0.0
        sampled = self._dispatch(1, t, payload)
        host = None
        out: List[Optional[int]] = []
        lps: List[Optional[tuple]] = []
        for i, chunk in enumerate(chunks):
            if chunk.is_last_chunk:
                if host is None:
                    host = jax.device_get(sampled)
                if want_lp:
                    out.append(int(host[0][i]))
                    lps.append(
                        self._lp_entry(chunk.seq, host[1][i],
                                       host[2][i], host[3][i])
                        if chunk.seq.sampling.logprobs else None)
                else:
                    out.append(int(host[i]))
                    lps.append(None)
            else:
                out.append(None)
                lps.append(None)
        if _TIMING:
            if host is None:  # async dispatch: sync so the wall is real
                jax.device_get(sampled)
            self._record_timing("prefill", t, time.perf_counter() - t0)
        return out, (lps if want_lp else None)

    # ---- decode -----------------------------------------------------------

    def _staging_set(self) -> dict:
        """Next reusable host staging buffer set (double-buffered; see
        __init__). Arrays are zero-reset here so None/pad rows are
        masked (valid False) and read the trash page (table 0)."""
        if self._decode_staging is None:
            b, p = self.decode_width, self.max_pages_per_seq

            def one():
                buf = {
                    # [B] not [B, 1]: the step program reshapes on
                    # device, so an ahead dispatch can feed the
                    # previous step's [B] sampled array directly.
                    "tokens": np.zeros((b,), np.int32),
                    "positions": np.zeros((b, 1), np.int32),
                    "valid": np.zeros((b, 1), bool),
                    "page_table": np.zeros((b, p), np.int32),
                    "kv_lens": np.zeros((b,), np.int32),
                    "last_index": np.zeros((b,), np.int32),
                    "temperature": np.zeros((b,), np.float32),
                    "top_p": np.ones((b,), np.float32),
                    "top_k": np.zeros((b,), np.int32),
                }
                if self.lora_registry is not None:
                    buf["lora_ids"] = np.zeros((b,), np.int32)
                return buf

            self._decode_staging = (one(), one())
        st = self._decode_staging[self._staging_idx]
        self._staging_idx ^= 1
        for name, arr in st.items():
            arr.fill(1 if name == "top_p" else 0)
        return st

    def dispatch_decode(self, rows, token_source=None,
                        ahead: bool = False) -> DecodeStepHandle:
        """Build and dispatch ONE single-step decode program with no
        blocking host read anywhere on the path (the AST lint
        tests/test_dispatch_path_lint.py enforces this statically).

        The synchronous engine uses it too (run_decode's single-step
        path), so sync and async greedy decoding share one dispatch
        path and byte-exact parity is structural, not incidental.

        ``rows``: the batch's sequences; None entries (plan-ahead
        slots whose row is already known to finish) dispatch as
        masked pad rows so the batch shape — and row alignment with
        ``token_source`` — never changes. ``token_source``: the
        previous step's sampled-token device array ([B]); when given,
        this step's input tokens never touch the host. ``ahead``
        shifts positions/kv_lens by the one token the in-flight step
        will have committed by the time this program's inputs are
        consumed.
        """
        if self.bridge is not None:
            raise NotImplementedError(
                "async dispatch over the multihost step bridge (the "
                "step broadcast ships host-resident numpy payloads)")
        b = self.decode_width
        rows = list(rows)[:b]
        st = self._staging_set()
        off = 1 if ahead else 0
        page_table = st["page_table"]
        # During a pure-decode stretch only positions/kv_lens (+1 per
        # step) and the input tokens actually change; the per-row
        # static inputs (valid mask, page table, sampling knobs, lora
        # ids) are reused as the *device arrays* of the previous
        # dispatch while this signature — row identity, liveness
        # pattern, and exact page list — is unchanged. Sampling params
        # and lora ids are immutable after admission, so they need no
        # signature term beyond the seq id.
        sig = tuple((seq.seq_id, tuple(seq.pages))
                    if seq is not None else None for seq in rows)
        cached = self._decode_static_cache
        reuse = cached is not None and cached[0] == sig
        stochastic = False
        for i, seq in enumerate(rows):
            if seq is None:
                continue
            if token_source is None:
                st["tokens"][i] = (seq.output_token_ids[-1]
                                   if seq.output_token_ids
                                   else seq.prompt_token_ids[-1])
            st["positions"][i, 0] = seq.total_len - 1 + off
            st["kv_lens"][i] = seq.total_len + off
            sp = seq.sampling
            if sp.temperature > 0:
                stochastic = True
            if reuse:
                continue
            st["valid"][i, 0] = True
            st["temperature"][i] = sp.temperature
            st["top_p"][i] = sp.top_p
            st["top_k"][i] = sp.top_k
            n = min(len(seq.pages), self.max_pages_per_seq)
            page_table[i, :n] = seq.pages[:n]
            if self.lora_registry is not None:
                st["lora_ids"][i] = seq.lora_id
        # ONE fused host->device transfer for the (changed part of
        # the) input set — replaces the per-array jnp.asarray shower.
        # An ahead dispatch additionally excludes the tokens buffer:
        # its tokens are the previous step's sampled [B] int32 device
        # array, consumed verbatim — no transfer, no eager
        # cast/reshape (the step program reshapes on device).
        dynamic = ("positions", "kv_lens") + (
            ("tokens",) if token_source is None else ())
        names = (dynamic if reuse else
                 tuple(n for n in st
                       if token_source is None or n != "tokens"))
        # Static entries are snapshotted (.copy()): on the CPU
        # backend device_put of a numpy array may be ZERO-copy, and
        # the cached device arrays must not alias a staging buffer
        # that later steps zero-reset and refill.
        devs = jax.device_put(tuple(
            st[n] if n in dynamic else st[n].copy() for n in names))
        payload = dict(zip(names, devs))
        if reuse:
            payload.update(cached[1])
        else:
            self._decode_static_cache = (sig, {
                n: payload[n] for n in payload
                if n not in ("tokens", "positions", "kv_lens")})
        if token_source is not None:
            payload["tokens"] = token_source
        # The rng key stays a device array (no host readback; the
        # multihost numpy conversion is unreachable here). An
        # all-greedy batch never consumes the key (temperature 0
        # short-circuits sampling), so skip the per-step split — a
        # real eager dispatch — and pass the stream head unadvanced.
        payload["rng"] = self._next_rng() if stochastic else self._rng
        if not ahead:
            # Per-row optional inputs (penalties/seed/bias/suppress/
            # guided) for the sync single-step path. Plan-ahead
            # eligibility guarantees these are all {} for ahead
            # dispatches (their host state is one token stale), so
            # those skip the five row scans outright.
            payload.update(self._penalty_payload(rows, b))
            payload.update(self._seed_payload(rows, b))
            payload.update(self._bias_payload(rows, b))
            payload.update(self._suppress_payload(rows, b))
            payload.update(self._guided_payload(rows, b))
        want_lp = any(s is not None and s.sampling.logprobs
                      for s in rows)
        if want_lp:
            payload["want_logprobs"] = True
        sampled = self._dispatch(2, 1, payload)
        return DecodeStepHandle(self, rows, sampled, want_lp)

    def run_decode(self, plan: DecodePlan
                   ) -> Tuple[List[List[int]], Optional[list]]:
        """One decode dispatch over all running sequences (padded
        batch); returns (token_lists, logprob_lists) — logprob_lists
        is None unless a row requested logprobs. With a multi-step
        window the burst program evaluates per-row budgets and stop
        sets on device, so one dispatch + one device_get covers up to
        ``window`` tokens per row even when rows finish mid-burst."""
        if plan.drafts is not None:
            return self._run_spec_decode(plan)
        seqs = plan.seqs[: self.decode_width]
        b = self.decode_width
        window = max(1, plan.window)
        if window == 1 and self.bridge is None:
            # Single-host single-step decode rides the async
            # pipeline's dispatch path (staged inputs, one fused
            # transfer, one fused device_get) even in sync mode, so
            # sync-vs-async parity is the same code path.
            t0 = time.perf_counter() if _TIMING else 0.0
            out = self.dispatch_decode(seqs).result()
            if _TIMING:
                self._record_timing("decode", 1,
                                    time.perf_counter() - t0)
            return out
        stop_w = STOP_SET_WIDTH

        tokens = np.zeros((b, 1), np.int32)
        positions = np.zeros((b, 1), np.int32)
        valid = np.zeros((b, 1), bool)
        kv_lens = np.zeros((b,), np.int32)
        budgets = np.zeros((b,), np.int32)
        stop_tokens = np.full((b, stop_w), -1, np.int32)
        # Pad rows stay temperature 0 so an all-greedy batch keeps the
        # sampler's sort-free fast path (ops/sampling.py).
        temperature = np.zeros((b,), np.float32)
        top_p = np.ones((b,), np.float32)
        top_k = np.zeros((b,), np.int32)

        for i, seq in enumerate(seqs):
            last_token = (seq.output_token_ids[-1]
                          if seq.output_token_ids
                          else seq.prompt_token_ids[-1])
            tokens[i, 0] = last_token
            positions[i, 0] = seq.total_len - 1
            valid[i, 0] = True
            kv_lens[i] = seq.total_len
            budgets[i] = decode_budget(
                seq, self.config.scheduler.max_model_len)
            if not seq.sampling.ignore_eos:
                ids = seq.sampling.stop_token_ids[:stop_w]
                stop_tokens[i, : len(ids)] = ids
            temperature[i] = seq.sampling.temperature
            top_p[i] = seq.sampling.top_p
            top_k[i] = seq.sampling.top_k

        payload = {
            "tokens": tokens,
            "positions": positions,
            "valid": valid,
            "page_table": self._page_table_rows(seqs, pad_to=b),
            "kv_lens": kv_lens,
            "last_index": np.zeros((b,), np.int32),
            "temperature": temperature,
            "top_p": top_p,
            "top_k": top_k,
            "rng": np.asarray(self._next_rng()),
        }
        if window > 1:
            payload["active"] = valid[:, 0].copy()
            payload["budgets"] = budgets
            payload["stop_tokens"] = stop_tokens
        if self.lora_registry is not None:
            ids = np.zeros((b,), np.int32)
            for i, seq in enumerate(seqs):
                ids[i] = seq.lora_id
            payload["lora_ids"] = ids
        payload.update(self._penalty_payload(seqs, b))
        payload.update(self._seed_payload(seqs, b))
        payload.update(self._bias_payload(seqs, b))
        payload.update(self._suppress_payload(seqs, b))
        payload.update(self._guided_payload(seqs, b))
        want_lp = any(s.sampling.logprobs for s in seqs)
        if want_lp:
            payload["want_logprobs"] = True

        t0 = time.perf_counter() if _TIMING else 0.0
        sampled = self._dispatch(2, window, payload)
        host = jax.device_get(sampled)
        if _TIMING:
            self._record_timing("decode", window,
                                time.perf_counter() - t0)
        if not want_lp:
            if window == 1:
                return [[int(host[i])] for i in range(len(seqs))], None
            return [[int(host[k, i]) for k in range(window)
                     if host[k, i] >= 0]
                    for i in range(len(seqs))], None
        toks, slp, tids, tlps = host
        if window == 1:
            return ([[int(toks[i])] for i in range(len(seqs))],
                    [[self._lp_entry(seqs[i], slp[i], tids[i],
                                     tlps[i])
                      if seqs[i].sampling.logprobs else None]
                     for i in range(len(seqs))])
        token_lists, lp_lists = [], []
        for i, seq in enumerate(seqs):
            row_t, row_l = [], []
            for k in range(window):
                if toks[k, i] < 0:
                    continue
                row_t.append(int(toks[k, i]))
                row_l.append(
                    self._lp_entry(seq, slp[k, i], tids[k, i],
                                   tlps[k, i])
                    if seq.sampling.logprobs else None)
            token_lists.append(row_t)
            lp_lists.append(row_l)
        return token_lists, lp_lists

    def dispatch_spec(self, plan: DecodePlan) -> SpecStepHandle:
        """Build and dispatch ONE speculative verify step with no
        blocking host read on the path (docs/speculative.md).

        Every running row rides the same fixed [B, S] program: rows
        with a draft verify it, rows without (draft_len 0) decode
        exactly one token through the identical shape — occupancy and
        acceptance counts never change the compiled program. The
        handle's ``result()`` parses each row's accepted prefix plus
        the bonus/resample token (1..S tokens, order-correct); its
        ``token_source`` lets the async pipeline chain an
        assume-one-token successor before the readback. The scheduler
        guarantees row eligibility and that pages cover
        total_len + draft_len.
        """
        from production_stack_tpu.parallel.distributed import KIND_SPEC
        seqs = plan.seqs[: self.decode_width]
        b = self.decode_width
        s = self.spec_width

        tokens = np.zeros((b, s), np.int32)
        positions = np.zeros((b, s), np.int32)
        valid = np.zeros((b, s), bool)
        kv_lens = np.zeros((b,), np.int32)
        drafts = np.full((b, s - 1), -1, np.int32)
        draft_lens = np.zeros((b,), np.int32)
        # Pad rows stay temperature 0 so an all-greedy batch keeps the
        # verify rule's argmax-only fast path (ops/sampling.py).
        temperature = np.zeros((b,), np.float32)
        top_p = np.ones((b,), np.float32)
        top_k = np.zeros((b,), np.int32)

        for i, seq in enumerate(seqs):
            d = plan.drafts[i]
            n = 1 + len(d)
            tokens[i, 0] = (seq.output_token_ids[-1]
                           if seq.output_token_ids
                           else seq.prompt_token_ids[-1])
            tokens[i, 1:n] = d
            positions[i, :n] = np.arange(seq.total_len - 1,
                                         seq.total_len - 1 + n)
            valid[i, :n] = True
            kv_lens[i] = seq.total_len + len(d)
            drafts[i, :len(d)] = d
            draft_lens[i] = len(d)
            temperature[i] = seq.sampling.temperature
            top_p[i] = seq.sampling.top_p
            top_k[i] = seq.sampling.top_k

        payload = {
            "tokens": tokens,
            "positions": positions,
            "valid": valid,
            "page_table": self._page_table_rows(seqs, pad_to=b),
            "kv_lens": kv_lens,
            "last_index": np.zeros((b,), np.int32),
            "temperature": temperature,
            "top_p": top_p,
            "top_k": top_k,
            "rng": np.asarray(self._next_rng()),
            "drafts": drafts,
            "draft_lens": draft_lens,
        }
        if self.lora_registry is not None:
            ids = np.zeros((b,), np.int32)
            for i, seq in enumerate(seqs):
                ids[i] = seq.lora_id
            payload["lora_ids"] = ids
        want_lp = any(q.sampling.logprobs for q in seqs)
        if want_lp:
            payload["want_logprobs"] = True

        sampled = self._dispatch(KIND_SPEC, s, payload)
        return SpecStepHandle(
            self, list(seqs),
            [list(plan.drafts[i]) for i in range(len(seqs))],
            sampled, want_lp)

    def _run_spec_decode(self, plan: DecodePlan
                         ) -> Tuple[List[List[int]], Optional[list]]:
        """Synchronous verify step: dispatch + immediate readback."""
        t0 = time.perf_counter() if _TIMING else 0.0
        out = self.dispatch_spec(plan).result()
        if _TIMING:
            self._record_timing("spec", self.spec_width,
                                time.perf_counter() - t0)
        return out

    # ---- unified ragged step (docs/unified_step.md) -----------------------

    def run_unified(self, plan):
        """Execute one genuinely mixed step: decode/draft rows and
        prefill chunk rows in ONE fixed-shape [R, W] ragged program.

        Row layout (the per-row descriptor is the
        kv_lens/last_index/draft_lens triple — docs/unified_step.md):
        compact — decode rows at 0..len(seqs)-1 (aligned with
        plan.decode.seqs), prefill chunk rows immediately after
        (aligned with plan.prefill.chunks), pads only at the tail.
        R snaps to the closed ``unified_row_buckets`` lattice so the
        compiled shape depends on occupancy only through the (row
        bucket, W bucket) pair, never on batch composition. Returns
        (decode_token_lists, decode_lp_lists, prefill_tokens,
        prefill_lp_rows): decode rows commit 1..span tokens (the
        verify contract), prefill rows one sampled token for last
        chunks (None mid-prompt).
        """
        from production_stack_tpu.parallel.distributed import (
            KIND_UNIFIED,
        )
        seqs = plan.decode.seqs[: self.decode_width]
        chunks = plan.prefill.chunks[: self.prefill_width]
        spec_drafts = plan.decode.drafts
        off = len(seqs)
        r = self._row_bucket_for(off + len(chunks))
        self.last_unified_rows = r
        s = self.unified_span
        w = max(self._bucket_for(
            max(len(c.chunk_tokens) for c in chunks)), s)

        tokens = np.zeros((r, w), np.int32)
        positions = np.zeros((r, w), np.int32)
        valid = np.zeros((r, w), bool)
        kv_lens = np.zeros((r,), np.int32)
        last_index = np.zeros((r,), np.int32)
        drafts = np.full((r, s - 1), -1, np.int32)
        draft_lens = np.zeros((r,), np.int32)
        # Pad rows stay temperature 0 so an all-greedy batch keeps
        # the verify rule's argmax-only fast path (ops/sampling.py).
        temperature = np.zeros((r,), np.float32)
        top_p = np.ones((r,), np.float32)
        top_k = np.zeros((r,), np.int32)
        page_table = np.zeros((r, self.max_pages_per_seq), np.int32)
        lora_ids = (np.zeros((r,), np.int32)
                    if self.lora_registry is not None else None)

        def _row_static(i, seq):
            temperature[i] = seq.sampling.temperature
            top_p[i] = seq.sampling.top_p
            top_k[i] = seq.sampling.top_k
            n = min(len(seq.pages), self.max_pages_per_seq)
            page_table[i, :n] = seq.pages[:n]
            if lora_ids is not None:
                lora_ids[i] = seq.lora_id

        for i, seq in enumerate(seqs):
            d = (spec_drafts[i] if spec_drafts is not None else ())
            n = 1 + len(d)
            tokens[i, 0] = (seq.output_token_ids[-1]
                            if seq.output_token_ids
                            else seq.prompt_token_ids[-1])
            tokens[i, 1:n] = d
            positions[i, :n] = np.arange(seq.total_len - 1,
                                         seq.total_len - 1 + n)
            valid[i, :n] = True
            kv_lens[i] = seq.total_len + len(d)
            last_index[i] = n - 1
            drafts[i, :len(d)] = d
            draft_lens[i] = len(d)
            _row_static(i, seq)

        for j, chunk in enumerate(chunks):
            i = off + j
            n = len(chunk.chunk_tokens)
            tokens[i, :n] = chunk.chunk_tokens
            positions[i, :n] = np.arange(chunk.chunk_start,
                                         chunk.chunk_start + n)
            valid[i, :n] = True
            kv_lens[i] = chunk.chunk_start + n
            last_index[i] = n - 1
            _row_static(i, chunk.seq)

        payload = {
            "tokens": tokens,
            "positions": positions,
            "valid": valid,
            "page_table": page_table,
            "kv_lens": kv_lens,
            "last_index": last_index,
            "drafts": drafts,
            "draft_lens": draft_lens,
            "temperature": temperature,
            "top_p": top_p,
            "top_k": top_k,
            "rng": np.asarray(self._next_rng()),
        }
        if lora_ids is not None:
            payload["lora_ids"] = lora_ids
        sampling_rows = (list(seqs)
                         + [c.seq for c in chunks if c.is_last_chunk])
        want_lp = any(q.sampling.logprobs for q in sampling_rows)
        if want_lp:
            payload["want_logprobs"] = True

        t0 = time.perf_counter() if _TIMING else 0.0
        sampled = self._dispatch(KIND_UNIFIED, w, payload)
        host = jax.device_get(sampled)
        if _TIMING:
            self._record_timing("unified", w,
                                time.perf_counter() - t0)
        if want_lp:
            toks, slp, tids, tlps = host
        else:
            toks = host
        token_lists, lp_lists = [], []
        for i, seq in enumerate(seqs):
            row_t, row_l = [], []
            for j in range(s):
                if toks[i, j] < 0:
                    break
                row_t.append(int(toks[i, j]))
                if want_lp:
                    row_l.append(
                        self._lp_entry(seq, slp[i, j], tids[i, j],
                                       tlps[i, j])
                        if seq.sampling.logprobs else None)
            token_lists.append(row_t)
            lp_lists.append(row_l)
        prefill_out, prefill_lps = [], []
        for j, chunk in enumerate(chunks):
            i = off + j
            if not chunk.is_last_chunk:
                prefill_out.append(None)
                prefill_lps.append(None)
                continue
            prefill_out.append(int(toks[i, 0]))
            prefill_lps.append(
                self._lp_entry(chunk.seq, slp[i, 0], tids[i, 0],
                               tlps[i, 0])
                if want_lp and chunk.seq.sampling.logprobs else None)
        return (token_lists, lp_lists if want_lp else None,
                prefill_out, prefill_lps if want_lp else None)

    # ---- page-granular IO (offload tiers) ---------------------------------

    def read_page(self, page_id: int) -> Tuple[np.ndarray, ...]:
        """Copy one page's KV out of HBM: [L, kv, d, page_size] each.

        The offload serde page format is layer-stacked regardless of
        the HBM layout, so tiers and the remote cache server stay
        layout-agnostic.  Quantized caches return a 4-tuple
        (k, v, k_scale, v_scale) with [L, kv, page_size] scales.
        """
        if self.kv_quantized:
            if self.cache_layout == "per_layer":
                k = np.stack(jax.device_get(
                    [kc.data[:, page_id] for kc in self.k_cache]))
                v = np.stack(jax.device_get(
                    [vc.data[:, page_id] for vc in self.v_cache]))
                ks = np.stack(jax.device_get(
                    [kc.scale[:, page_id] for kc in self.k_cache]))
                vs = np.stack(jax.device_get(
                    [vc.scale[:, page_id] for vc in self.v_cache]))
                return k, v, ks, vs
            k = jax.device_get(self.k_cache.data[:, :, page_id])
            v = jax.device_get(self.v_cache.data[:, :, page_id])
            ks = jax.device_get(self.k_cache.scale[:, :, page_id])
            vs = jax.device_get(self.v_cache.scale[:, :, page_id])
            return k, v, ks, vs
        if self.cache_layout == "per_layer":
            k = np.stack(jax.device_get(
                [kc[:, page_id] for kc in self.k_cache]))
            v = np.stack(jax.device_get(
                [vc[:, page_id] for vc in self.v_cache]))
            return k, v
        k = jax.device_get(self.k_cache[:, :, page_id])
        v = jax.device_get(self.v_cache[:, :, page_id])
        return k, v

    def write_page(self, page_id: int, k_page: np.ndarray,
                   v_page: np.ndarray,
                   k_scale: Optional[np.ndarray] = None,
                   v_scale: Optional[np.ndarray] = None) -> None:
        """Restore one page's KV into HBM (donated in-place update)."""
        if self.kv_quantized:
            self._write_page_quantized(page_id, k_page, v_page,
                                       k_scale, v_scale)
            return
        if not hasattr(self, "_write_page_jit"):
            self._write_page_jit = jax.jit(
                lambda cache, page, pid:
                    cache.at[:, :, pid].set(page.astype(cache.dtype)),
                donate_argnums=(0,),
            )
            self._write_layer_page_jit = jax.jit(
                lambda cache, page, pid:
                    cache.at[:, pid].set(page.astype(cache.dtype)),
                donate_argnums=(0,),
            )
        if self.cache_layout == "per_layer":
            self.k_cache = tuple(
                self._write_layer_page_jit(
                    kc, jnp.asarray(k_page[layer]), page_id)
                for layer, kc in enumerate(self.k_cache))
            self.v_cache = tuple(
                self._write_layer_page_jit(
                    vc, jnp.asarray(v_page[layer]), page_id)
                for layer, vc in enumerate(self.v_cache))
            return
        self.k_cache = self._write_page_jit(
            self.k_cache, jnp.asarray(k_page), page_id
        )
        self.v_cache = self._write_page_jit(
            self.v_cache, jnp.asarray(v_page), page_id
        )

    def _write_page_quantized(self, page_id: int, k_page: np.ndarray,
                              v_page: np.ndarray, k_scale: np.ndarray,
                              v_scale: np.ndarray) -> None:
        if k_scale is None or v_scale is None:
            raise ValueError(
                "quantized cache restore requires k_scale/v_scale")
        if not hasattr(self, "_write_page_q_jit"):
            self._write_page_q_jit = jax.jit(
                lambda cache, page, scale, pid: QuantKV(
                    cache.data.at[:, :, pid].set(
                        page.astype(jnp.int8)),
                    cache.scale.at[:, :, pid].set(
                        scale.astype(jnp.float32))),
                donate_argnums=(0,),
            )
            self._write_layer_page_q_jit = jax.jit(
                lambda cache, page, scale, pid: QuantKV(
                    cache.data.at[:, pid].set(
                        page.astype(jnp.int8)),
                    cache.scale.at[:, pid].set(
                        scale.astype(jnp.float32))),
                donate_argnums=(0,),
            )
        if self.cache_layout == "per_layer":
            self.k_cache = tuple(
                self._write_layer_page_q_jit(
                    kc, jnp.asarray(k_page[layer]),
                    jnp.asarray(k_scale[layer]), page_id)
                for layer, kc in enumerate(self.k_cache))
            self.v_cache = tuple(
                self._write_layer_page_q_jit(
                    vc, jnp.asarray(v_page[layer]),
                    jnp.asarray(v_scale[layer]), page_id)
                for layer, vc in enumerate(self.v_cache))
            return
        self.k_cache = self._write_page_q_jit(
            self.k_cache, jnp.asarray(k_page), jnp.asarray(k_scale),
            page_id)
        self.v_cache = self._write_page_q_jit(
            self.v_cache, jnp.asarray(v_page), jnp.asarray(v_scale),
            page_id)

    def _page_table_rows(self, seqs: List[Sequence],
                         pad_to: Optional[int] = None) -> np.ndarray:
        rows = pad_to or len(seqs)
        table = np.zeros((rows, self.max_pages_per_seq), np.int32)
        for i, seq in enumerate(seqs):
            n = min(len(seq.pages), self.max_pages_per_seq)
            table[i, :n] = seq.pages[:n]
        return table
