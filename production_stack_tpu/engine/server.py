"""OpenAI-compatible HTTP front end for the TPU engine.

This is the process the Helm chart's engine pods run (the counterpart of
``vllm serve`` in reference deployment-vllm-multi.yaml:57-103). Surface:

  POST /v1/chat/completions | /v1/completions   (stream + non-stream)
  GET  /v1/models | /health | /version
  GET  /metrics  -- vLLM exposition names the router scrapes
                    (reference engine_stats.py:46-55):
                    vllm:num_requests_running, vllm:num_requests_waiting,
                    vllm:gpu_cache_usage_perc, vllm:gpu_prefix_cache_hit_rate

Threading model: the device loop runs in one dedicated thread (JAX
dispatch is blocking); HTTP handlers submit requests through a
thread-safe queue and receive per-token deltas via asyncio queues.
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import json
import queue
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from aiohttp import web

from production_stack_tpu.engine.config import (
    AutotuneConfig,
    bench_1b_model_config,
    CacheConfig,
    EngineConfig,
    KVEconConfig,
    LoRAConfig,
    ModelConfig,
    OffloadConfig,
    ParallelConfig,
    QoSConfig,
    SchedulerConfig,
    tiny_model_config,
)
from production_stack_tpu.kvecon.summary import (
    PrefixSummaryTracker,
    routable_text,
)
from production_stack_tpu.engine.engine import LLMEngine
from production_stack_tpu.qos import (
    parse_priority,
    Priority,
    PRIORITY_HEADER,
    PRIORITY_NAMES,
    priority_name,
    shed_counter_dict,
    shed_retry_after_s,
    SPEC_OFF_HEADER,
)
from production_stack_tpu.engine.sequence import SamplingParams
from production_stack_tpu.engine.tokenizer import (
    get_tokenizer,
    render_chat_prompt,
)
from production_stack_tpu.utils.log import init_logger
from production_stack_tpu.version import __version__

logger = init_logger(__name__)


class AsyncEngine:
    """Background-thread engine loop with asyncio streaming outputs."""

    def __init__(self, engine: LLMEngine):
        self.engine = engine
        self._submit_q: "queue.Queue" = queue.Queue()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._streams: Dict[str, asyncio.Queue] = {}
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="engine-loop"
        )
        self._started = threading.Event()
        # Wakes the loop out of its idle/backoff waits the moment new
        # work arrives (submit/abort) instead of serving out a fixed
        # sleep — cuts TTFT for requests that land on an idle engine.
        self._wakeup = threading.Event()
        self.uptime_start = time.time()
        # Step watchdog (docs/crash_recovery.md): wall-clock start of
        # the step currently executing on the device thread, None
        # between steps. The asyncio /health handler reads it — a hung
        # device program blocks this thread, not the event loop.
        self._step_started: Optional[float] = None
        # Self-tuning (docs/autotuning.md): the EngineServer installs
        # an Autotuner here; the loop ticks it between steps so
        # controllers touch scheduler/config state from the same
        # thread that reads it. None = no tuning.
        self.autotuner = None

    def current_step_s(self) -> float:
        """Seconds the in-flight engine step has been running
        (0.0 when no step is executing)."""
        started = self._step_started
        if started is None:
            return 0.0
        return time.time() - started

    def start(self, loop: asyncio.AbstractEventLoop) -> None:
        self._loop = loop
        self._thread.start()
        self._started.set()

    def _run(self) -> None:
        from production_stack_tpu.engine.engine import StepOutput
        self._started.wait()
        while True:
            # Drain submissions (non-blocking when engine has work).
            block = not self.engine.has_work()
            try:
                item = self._submit_q.get(
                    block=block, timeout=1.0 if block else None
                )
            except queue.Empty:
                item = None
            if item is not None:
                seq_id = item["seq_id"]
                try:
                    if item.get("kind") == "handoff":
                        # Disagg decode role: park until the shipped
                        # KV is reachable (engine.add_handoff).
                        self.engine.add_handoff(
                            item["prompt"], item["first_token"],
                            item["sampling"], seq_id=seq_id,
                            request_id=item.get("request_id"),
                        )
                    elif item.get("kind") == "resume":
                        # Mid-stream failover: park until the
                        # checkpointed KV is reachable, or recompute
                        # from the journal (engine.add_resume).
                        self.engine.add_resume(
                            item["tokens"], item["prior"],
                            item["sampling"], seq_id=seq_id,
                            request_id=item.get("request_id"),
                        )
                    else:
                        self.engine.add_request(
                            item["prompt"], item["sampling"],
                            seq_id=seq_id,
                            lora_name=item.get("lora_name"),
                            handoff_prefill=item.get(
                                "handoff_prefill", False),
                            request_id=item.get("request_id"),
                            priority=item.get("priority"),
                            spec_off=item.get("spec_off", False),
                        )
                except Exception as e:
                    # Queue full / invalid request: fail THIS request,
                    # never the engine loop.
                    logger.warning("Rejecting %s: %s", seq_id, e)
                    self._emit(seq_id, StepOutput(
                        seq_id=seq_id, new_token=None, finished=True,
                        finish_reason="abort",
                    ))
                continue  # admit as many as possible before stepping
            if self.autotuner is not None:
                try:
                    self.autotuner.maybe_tick()
                except Exception:
                    logger.exception("autotune tick failed")
            if not self.engine.has_work():
                continue
            self._step_started = time.time()
            try:
                outputs = self.engine.step()
            except Exception as e:
                logger.exception("Engine step failed: %s", e)
                # Interruptible backoff: a new submission or abort
                # wakes the loop immediately instead of serving out
                # the full 50 ms.
                self._wakeup.wait(0.05)
                self._wakeup.clear()
                continue
            finally:
                self._step_started = None
            if not outputs:
                # Planner produced no executable work (e.g. transient
                # KV-cache starvation, or an async dispatch that owes
                # nothing yet): don't busy-spin, but let new arrivals
                # cut the wait short.
                self._wakeup.wait(0.002)
                self._wakeup.clear()
            for out in outputs:
                self._emit(out.seq_id, out)

    def _emit(self, seq_id: str, item) -> None:
        stream = self._streams.get(seq_id)
        if stream is None or self._loop is None:
            return
        self._loop.call_soon_threadsafe(stream.put_nowait, item)

    async def submit(self, prompt: List[int], sampling: SamplingParams,
                     lora_name: Optional[str] = None,
                     handoff_prefill: bool = False,
                     request_id: Optional[str] = None,
                     priority: Optional[int] = None,
                     spec_off: bool = False,
                     ) -> tuple[str, asyncio.Queue]:
        seq_id = f"seq-{uuid.uuid4().hex[:16]}"
        stream: asyncio.Queue = asyncio.Queue()
        self._streams[seq_id] = stream
        self._submit_q.put({
            "kind": "request", "prompt": prompt, "sampling": sampling,
            "seq_id": seq_id, "lora_name": lora_name,
            "handoff_prefill": handoff_prefill,
            "request_id": request_id,
            "priority": priority, "spec_off": spec_off,
        })
        self._wakeup.set()
        return seq_id, stream

    async def submit_handoff(self, prompt: List[int], first_token: int,
                             sampling: SamplingParams,
                             request_id: Optional[str] = None,
                             ) -> tuple[str, asyncio.Queue]:
        """Submit a disagg handoff descriptor's sequence
        (docs/disaggregation.md); the stream carries tokens FROM THE
        SECOND onward — the caller already has the first."""
        seq_id = f"seq-{uuid.uuid4().hex[:16]}"
        stream: asyncio.Queue = asyncio.Queue()
        self._streams[seq_id] = stream
        self._submit_q.put({
            "kind": "handoff", "prompt": prompt,
            "first_token": first_token, "sampling": sampling,
            "seq_id": seq_id, "request_id": request_id,
        })
        self._wakeup.set()
        return seq_id, stream

    async def submit_resume(self, tokens: List[int], prior: int,
                            sampling: SamplingParams,
                            request_id: Optional[str] = None,
                            ) -> tuple[str, asyncio.Queue]:
        """Submit a crashed stream's resume journal
        (docs/crash_recovery.md); the stream carries only NEW tokens —
        the journaled context is replayed by the handler."""
        seq_id = f"seq-{uuid.uuid4().hex[:16]}"
        stream: asyncio.Queue = asyncio.Queue()
        self._streams[seq_id] = stream
        self._submit_q.put({
            "kind": "resume", "tokens": tokens, "prior": prior,
            "sampling": sampling, "seq_id": seq_id,
            "request_id": request_id,
        })
        self._wakeup.set()
        return seq_id, stream

    def finish_stream(self, seq_id: str) -> None:
        self._streams.pop(seq_id, None)

    def abort(self, seq_id: str) -> None:
        self.engine.abort_request(seq_id)
        self.finish_stream(seq_id)
        self._wakeup.set()  # freed capacity: let the planner retry


# ---- request handling ------------------------------------------------------


def _sampling_from_body(body: dict, max_model_len: int,
                        vocab_size: "int | None" = None
                        ) -> SamplingParams:
    max_tokens = body.get("max_tokens")
    if max_tokens is None:
        max_tokens = body.get("max_completion_tokens")
    if max_tokens is None:
        max_tokens = 256  # OpenAI default; 0 is invalid, not "unset"
    # JSON null must fall back to the OpenAI defaults, not to 0.
    temperature = body.get("temperature")
    top_p = body.get("top_p")
    top_k = body.get("top_k")
    stop = body.get("stop")
    if stop is None:
        stop_strings = []
    elif isinstance(stop, str):
        stop_strings = [stop]
    else:
        stop_strings = [str(s) for s in stop][:4]  # OpenAI caps at 4
    presence = body.get("presence_penalty")
    frequency = body.get("frequency_penalty")
    repetition = body.get("repetition_penalty")  # vLLM extension
    # Chat API: logprobs is a bool + top_logprobs an int; legacy
    # completions API: logprobs is the top-k int itself.
    lp_req = body.get("logprobs")
    lp_top = int(body.get("top_logprobs") or 0)
    if isinstance(lp_req, bool):
        if not lp_req and lp_top > 0:
            raise ValueError(
                "'top_logprobs' is only allowed when 'logprobs' is "
                "enabled")
        lp_flag = lp_req
    elif lp_req is None:
        lp_flag = lp_top > 0
    else:
        lp_flag, lp_top = True, int(lp_req)
    # OpenAI logit_bias: {"<token_id>": bias} with string keys (JSON
    # object keys) and bias in [-100, 100], at most 300 entries.
    raw_bias = body.get("logit_bias")
    logit_bias = None
    if raw_bias:
        if not isinstance(raw_bias, dict):
            raise ValueError("logit_bias must be an object mapping "
                             "token ids to bias values")
        if len(raw_bias) > 300:
            raise ValueError("logit_bias supports at most 300 entries")
        logit_bias = {}
        for k, v in raw_bias.items():
            try:
                tid = int(k)
                bv = float(v)
            except (TypeError, ValueError):
                raise ValueError(
                    f"logit_bias entries must map integer token ids "
                    f"to numbers (got {k!r}: {v!r})")
            if not (-100.0 <= bv <= 100.0):
                raise ValueError(
                    f"logit_bias values must be in [-100, 100], got "
                    f"{bv} for token {tid}")
            if vocab_size is not None and not (0 <= tid < vocab_size):
                # Reject like every other out-of-range param — a
                # silently dropped ban (wrong tokenizer assumed) would
                # succeed while doing nothing.
                raise ValueError(
                    f"logit_bias token id {tid} is outside the model "
                    f"vocabulary (size {vocab_size})")
            logit_bias[tid] = bv
    params = SamplingParams(
        max_tokens=min(int(max_tokens), max_model_len),
        temperature=1.0 if temperature is None else float(temperature),
        top_p=1.0 if top_p is None else float(top_p),
        top_k=0 if top_k is None else int(top_k),
        stop_strings=stop_strings,
        presence_penalty=0.0 if presence is None else float(presence),
        frequency_penalty=(0.0 if frequency is None
                           else float(frequency)),
        repetition_penalty=(1.0 if repetition is None
                            else float(repetition)),
        ignore_eos=bool(body.get("ignore_eos", False)),
        seed=None if body.get("seed") is None else int(body["seed"]),
        logprobs=lp_flag,
        top_logprobs=lp_top,
        logit_bias=logit_bias,
        min_tokens=int(body.get("min_tokens") or 0),
        guided=_guided_from_body(body),
    )
    _validate_sampling(params)
    return params


def _guided_from_body(body: dict) -> "str | None":
    """OpenAI ``response_format`` -> guided mode ('json' or None)."""
    rf = body.get("response_format")
    if rf is None:
        return None
    if not isinstance(rf, dict) or "type" not in rf:
        raise ValueError(
            "response_format must be an object with a 'type' field")
    kind = rf["type"]
    if kind == "text":
        return None
    if kind == "json_object":
        return "json"
    raise ValueError(
        f"unsupported response_format type {kind!r} "
        "(supported: 'text', 'json_object')")


def _validate_sampling(p: SamplingParams) -> None:
    """Reject out-of-range sampling params with ValueError (the caller
    maps it to HTTP 400, matching OpenAI/vLLM behavior) instead of
    letting them reach the device, where e.g. repetition_penalty=0
    divides logits and emits NaN garbage with a 200."""
    if p.max_tokens < 1:
        raise ValueError("max_tokens must be at least 1")
    if not (0.0 <= p.temperature <= 2.0):
        raise ValueError(
            f"temperature must be in [0, 2], got {p.temperature}")
    if not (0.0 < p.top_p <= 1.0):
        raise ValueError(f"top_p must be in (0, 1], got {p.top_p}")
    if p.top_k < 0:
        raise ValueError(
            f"top_k must be a non-negative integer, got {p.top_k}")
    if not (-2.0 <= p.presence_penalty <= 2.0):
        raise ValueError(
            f"presence_penalty must be in [-2, 2], got "
            f"{p.presence_penalty}")
    if not (-2.0 <= p.frequency_penalty <= 2.0):
        raise ValueError(
            f"frequency_penalty must be in [-2, 2], got "
            f"{p.frequency_penalty}")
    if p.repetition_penalty <= 0.0:
        raise ValueError(
            f"repetition_penalty must be a positive number, got "
            f"{p.repetition_penalty}")
    if not (0 <= p.top_logprobs <= 20):
        raise ValueError(
            f"top_logprobs must be in [0, 20], got {p.top_logprobs}")
    if not (0 <= p.min_tokens <= p.max_tokens):
        raise ValueError(
            f"min_tokens must be in [0, max_tokens], got "
            f"{p.min_tokens} with max_tokens {p.max_tokens}")


def _sampling_to_wire(p: SamplingParams) -> dict:
    """SamplingParams -> JSON-safe dict for a handoff descriptor."""
    d = dict(vars(p))
    if d.get("logit_bias"):
        # JSON object keys are strings; _sampling_from_wire restores
        # the int token ids.
        d["logit_bias"] = {str(k): v
                           for k, v in d["logit_bias"].items()}
    return d


def _sampling_from_wire(d: dict) -> SamplingParams:
    """Inverse of _sampling_to_wire; unknown keys are dropped so a
    newer prefill engine can hand off to an older decode engine."""
    d = dict(d)
    lb = d.get("logit_bias")
    if lb:
        d["logit_bias"] = {int(k): float(v) for k, v in lb.items()}
    allowed = {f.name for f in dataclasses.fields(SamplingParams)}
    return SamplingParams(**{k: v for k, v in d.items()
                             if k in allowed})


class _StopStringScanner:
    """Incremental OpenAI ``stop``-sequence detection on decoded text.

    Stop sequences are a TEXT contract: a stop string can span token
    boundaries, so it cannot be evaluated on token ids in the engine.
    The scanner holds back the last ``max(len(stop)) - 1`` characters
    of the stream; on a hit it emits only the text before the stop
    (OpenAI semantics: the stop sequence itself is not returned) and
    flags ``stopped`` so the caller aborts the engine sequence.
    """

    def __init__(self, stops):
        self.stops = [s for s in stops if s]
        self.hold = (max(len(s) for s in self.stops) - 1
                     if self.stops else 0)
        self.buf = ""
        self.stopped = False

    def feed(self, delta: str) -> str:
        if self.stopped or not delta:
            return ""
        if not self.stops:
            return delta
        self.buf += delta
        hit = -1
        for s in self.stops:
            j = self.buf.find(s)
            if j != -1 and (hit == -1 or j < hit):
                hit = j
        if hit != -1:
            self.stopped = True
            out, self.buf = self.buf[:hit], ""
            return out
        if len(self.buf) > self.hold:
            cut = len(self.buf) - self.hold
            out, self.buf = self.buf[:cut], self.buf[cut:]
            return out
        return ""

    def flush(self) -> str:
        """Emit any held-back tail once the stream ends unstopped."""
        if self.stopped:
            return ""
        out, self.buf = self.buf, ""
        return out


def _usage(prompt_len: int, completion_len: int) -> dict:
    return {
        "prompt_tokens": prompt_len,
        "completion_tokens": completion_len,
        "total_tokens": prompt_len + completion_len,
    }


class EngineServer:
    def __init__(self, engine: LLMEngine, served_model_name: str,
                 pooling: str = "last",
                 profile_dir: Optional[str] = None,
                 chat_template: Optional[str] = None,
                 drain_exit_timeout_s: float = 0.0,
                 build_id: str = ""):
        self.async_engine = AsyncEngine(engine)
        self.engine = engine
        self.model_name = served_model_name
        self.tokenizer = engine.tokenizer
        self.pooling = pooling
        self._embedder = None
        self._embed_lock = asyncio.Lock()
        self.profile_dir = profile_dir
        self._profiling = False
        # Synthetic span id for the active profiler capture window, so
        # the capture shows up in traceview next to the requests it
        # overlapped (docs/observability.md).
        self._profiler_span_id: Optional[str] = None
        # Jinja source overriding the model's chat template (vLLM's
        # --chat-template; a path is read by main()).
        self.chat_template = chat_template
        # Zero-loss drain (docs/fleet.md): once POST /drain flips this,
        # new admissions get 503+Retry-After (the resilience layer's
        # retryable-rejection semantics) while in-flight generation
        # requests run to completion untouched.
        self.draining = False
        self.drain_exit_timeout_s = drain_exit_timeout_s
        # Rolling upgrades (docs/fleet.md): --build-id labels the
        # running revision in /health and /version so the rollout
        # controller can verify which build a replica actually runs.
        # A migrate-mode drain flips migrate_drain: checkpointed
        # streams are cut right after a checkpoint frame so the router
        # resumes them on a new-revision replica instead of waiting
        # for multi-minute streams to finish here.
        self.build_id = build_id
        self.migrate_drain = False
        self._active_generations = 0
        self._drain_exit_task: Optional[asyncio.Task] = None
        # QoS graceful shedding (docs/qos.md): per-priority-class count
        # of requests turned away with 429 at the shed gate. Rendered
        # as vllm:qos_shed_total{class=...} on /metrics.
        self.qos_shed_counts = shed_counter_dict()
        # Step watchdog (docs/crash_recovery.md): latched once per hung
        # step so the trip is logged/span-evented once, not per probe.
        self._watchdog_tripped = False
        # Cluster KV economy (docs/kv_economy.md): decayed hot-prefix
        # tracker behind GET /kv/summary. Observed from the request
        # text at admission (O(prompt) hashing, no per-step cost); the
        # router's KVStateAwarePolicy hashes the same text domain so
        # the chain hashes line up.
        kve = getattr(engine.config, "kvecon", None) or KVEconConfig()
        self.kv_summary = PrefixSummaryTracker(
            top_k=kve.summary_top_k, admit_hits=kve.admit_hits,
            ttl_s=kve.ttl_s)
        # Topology observability (docs/parallelism.md): which slice
        # this process's first local device belongs to, resolved once
        # (jax.devices() order is stable for the process lifetime).
        self._slice_id_cache: Optional[int] = None
        # Self-tuning controllers (docs/autotuning.md). Constructed
        # unconditionally — maybe_tick() is a cheap no-op in 'off'
        # mode — so /autotune/status always answers and flipping the
        # mode needs no re-wiring.
        from production_stack_tpu.autotune import (
            Autotuner, build_engine_controllers,
            observatory_drift_flags)
        at = (getattr(engine.config, "autotune", None)
              or AutotuneConfig())
        try:
            controllers = build_engine_controllers(self, at)
            drift_flags = observatory_drift_flags(engine.runner)
        except AttributeError:
            # Stub engines (tests) lack the scheduler/metrics surface
            # the catalog reads; they still get a live, empty
            # autotuner so /autotune/status answers.
            controllers, drift_flags = [], None
        self.autotuner = Autotuner(
            at, controllers,
            tracer=getattr(engine, "tracer", None),
            drift_flags=drift_flags)
        self.async_engine.autotuner = self.autotuner

    def _slice_id(self) -> int:
        if self._slice_id_cache is None:
            try:
                from production_stack_tpu.parallel.topology import (
                    discover_topology,
                )
                import jax
                topo = discover_topology(
                    num_slices=self.engine.config.parallel.num_slices)
                self._slice_id_cache = topo.slice_of(
                    jax.local_devices()[0])
            except Exception:
                self._slice_id_cache = 0
        return self._slice_id_cache

    # -- decoding helpers ---------------------------------------------------

    def _delta_decoder(self):
        """Incremental detokenizer: feed token ids, get new text.

        ``push(tok)`` returns newly-decoded text (holding back a tail
        that may be an incomplete UTF-8/BPE run); ``push(None,
        flush=True)`` force-emits whatever is still held back (stream
        end).
        """
        tokens: List[int] = []
        base = 0  # tokens[:base] are already emitted

        def push(token_id: Optional[int], flush: bool = False) -> str:
            nonlocal base
            if token_id is not None:
                tokens.append(token_id)
            # Decode only the pending tail (O(1) per token, not O(n)).
            tail = self.tokenizer.decode(tokens[base:])
            if not flush and tail.endswith("�"):
                return ""  # likely an incomplete UTF-8/BPE run
            base = len(tokens)
            return tail

        return push

    # -- handlers -----------------------------------------------------------

    @staticmethod
    async def _json_body(request: web.Request) -> dict:
        try:
            body = await request.json()
        except Exception:
            raise web.HTTPBadRequest(
                text='{"error": {"message": "Request body is not valid '
                     'JSON"}}',
                content_type="application/json",
            )
        if not isinstance(body, dict):
            raise web.HTTPBadRequest(
                text='{"error": {"message": "Request body must be a '
                     'JSON object"}}',
                content_type="application/json",
            )
        return body

    async def chat_completions(self, request: web.Request):
        body = await self._json_body(request)
        messages = body.get("messages")
        if not isinstance(messages, list):
            return web.json_response(
                {"error": {"message": "'messages' must be a list"}},
                status=400,
            )
        prompt = render_chat_prompt(self.tokenizer, messages,
                                    chat_template=self.chat_template)
        self.kv_summary.observe_text(routable_text(body))
        return await self._generate_response(
            request, body, prompt, chat=True
        )

    async def completions(self, request: web.Request):
        body = await self._json_body(request)
        if body.get("suffix"):
            return web.json_response(
                {"error": {"message": "'suffix' (insertion) is not "
                                      "supported",
                           "type": "invalid_request_error"}},
                status=400,
            )
        prompt_in = body.get("prompt", "")
        if isinstance(prompt_in, list) and prompt_in and isinstance(
                prompt_in[0], int):
            prompt = list(prompt_in)
            prompt_text = None  # token-array prompt: decode for echo
        elif isinstance(prompt_in, list):
            prompt_text = "".join(prompt_in)
            prompt = self.tokenizer.encode(prompt_text)
        else:
            prompt_text = str(prompt_in)
            prompt = self.tokenizer.encode(prompt_text)
        self.kv_summary.observe_text(routable_text(body))
        return await self._generate_response(
            request, body, prompt, chat=False, prompt_text=prompt_text
        )

    def _qos_admit(self, request: web.Request):
        """Parse the request's QoS class and apply the shed gate.

        Returns ``(priority, spec_off, rejection)``. An unparseable
        ``x-priority`` header is the caller's bug -> 400. Under queue
        pressure (waiting depth at or past ``qos.shed_threshold`` of
        ``--max-queue-len``) non-interactive classes are turned away
        with an honest ``429 + Retry-After`` BEFORE they enter the
        engine queue — never a silent drop, never a 5xx; interactive
        requests are always admitted (the queue-full reject in
        ``Scheduler.add`` remains the hard backstop). Retry-After is
        queue_depth / running-slots (one request per slot-second is
        the deliberately pessimistic service-rate proxy; docs/qos.md).
        """
        raw = request.headers.get(PRIORITY_HEADER)
        if raw is None:
            priority = Priority(self.engine.default_priority)
        else:
            try:
                priority = parse_priority(raw)
            except ValueError as e:
                return None, False, web.json_response(
                    {"error": {"message": str(e),
                               "type": "invalid_request_error"}},
                    status=400,
                )
        spec_off = request.headers.get(SPEC_OFF_HEADER) == "1"
        qos = self.engine.config.qos
        max_queue = self.engine.config.scheduler.max_queue_len
        depth = self.engine.scheduler.num_waiting
        if (priority != Priority.INTERACTIVE
                and depth >= qos.shed_threshold * max_queue):
            retry_after = shed_retry_after_s(
                depth, max(1.0, float(self.engine.scheduler.num_running)))
            self.qos_shed_counts[priority_name(priority)] += 1
            return priority, spec_off, web.json_response(
                {"error": {"message": (
                    f"engine overloaded ({depth} requests waiting); "
                    f"{priority_name(priority)} requests are being "
                    f"shed — retry after {retry_after}s"),
                    "type": "overloaded_error"}},
                status=429, headers={"Retry-After": str(retry_after)},
            )
        return priority, spec_off, None

    async def _generate_response(self, request: web.Request, body: dict,
                                 prompt: List[int], chat: bool,
                                 prompt_text: Optional[str] = None):
        priority, spec_off, rejection = self._qos_admit(request)
        if rejection is not None:
            return rejection
        try:
            sampling = _sampling_from_body(
                body, self.engine.config.scheduler.max_model_len,
                vocab_size=self.engine.config.model.vocab_size,
            )
        except (ValueError, TypeError) as e:
            return web.json_response(
                {"error": {"message": str(e),
                           "type": "invalid_request_error"}},
                status=400,
            )
        stream_mode = bool(body.get("stream", False))
        created = int(time.time())
        rid = ("chatcmpl-" if chat else "cmpl-") + uuid.uuid4().hex[:16]

        max_prompt = self.engine.config.scheduler.max_model_len - 1
        if len(prompt) > max_prompt:
            return web.json_response(
                {"error": {"message": (
                    f"Prompt is {len(prompt)} tokens; maximum is "
                    f"{max_prompt} (max_model_len "
                    f"{self.engine.config.scheduler.max_model_len})"
                ), "type": "invalid_request_error"}},
                status=400,
            )

        # A request addressed to a registered adapter name runs with
        # that adapter; anything else runs the base model (the router
        # already filtered by served model name).
        requested = body.get("model")
        lora_name = (requested
                     if requested in self.engine.lora_names() else None)
        # Adapter-addressed requests echo the adapter name (vLLM does
        # the same so per-model client accounting stays correct).
        response_model = lora_name or self.model_name

        n = body.get("n")
        try:
            # 0 is invalid, not "default": only JSON null/absent means 1.
            n = 1 if n is None else int(n)
        except (TypeError, ValueError):
            n = -1
        if not 1 <= n <= 16:
            return web.json_response(
                {"error": {"message": "'n' must be an integer in "
                                      "[1, 16]",
                           "type": "invalid_request_error"}},
                status=400,
            )

        # Legacy /v1/completions best_of: generate best_of candidates
        # server-side, return the n with the highest mean token
        # logprob (the OpenAI contract; chat has no best_of).
        best_of = n
        if not chat and body.get("best_of") is not None:
            try:
                best_of = int(body["best_of"])
            except (TypeError, ValueError):
                best_of = -1
            if not n <= best_of <= 16:
                return web.json_response(
                    {"error": {"message": "'best_of' must be an "
                                          "integer in [n, 16]",
                               "type": "invalid_request_error"}},
                    status=400,
                )
            if stream_mode and best_of > n:
                return web.json_response(
                    {"error": {"message": "'best_of' > n cannot be "
                                          "streamed",
                               "type": "invalid_request_error"}},
                    status=400,
                )
        echo = bool(body.get("echo")) and not chat
        if echo and sampling.logprobs:
            return web.json_response(
                {"error": {"message": "'echo' with 'logprobs' (prompt "
                                      "logprobs) is not supported",
                           "type": "invalid_request_error"}},
                status=400,
            )
        # Echo returns the ORIGINAL prompt string when the client sent
        # text (decode(encode(s)) need not round-trip: special-token
        # text, sentencepiece normalization); token-array prompts are
        # decoded.
        echo_text = ""
        if echo:
            echo_text = (prompt_text if prompt_text is not None
                         else self.tokenizer.decode(prompt))

        candidates = best_of
        # Capture BEFORE the internal force below: legacy forms like
        # integer logprobs:0 or bare top_logprobs parse to
        # sampling.logprobs=True while bool(body["logprobs"]) is
        # falsy.
        requested_lp = sampling.logprobs
        if candidates > n and not sampling.logprobs:
            # Candidate ranking needs per-token logprobs internally;
            # the response omits them unless the client asked.
            sampling = dataclasses.replace(sampling, logprobs=True)

        # ``n`` choices = n engine sequences sharing one prompt; the
        # prefix cache makes the shared prompt prefill nearly free
        # after the first, and continuous batching decodes them as
        # ordinary batch rows. A seeded request derives per-choice
        # seeds (seed + i): seeded randomness is a pure function of
        # (seed, position), so identical seeds would make all n
        # choices byte-identical.
        def choice_sampling(i):
            if candidates == 1 or sampling.seed is None:
                return sampling
            return dataclasses.replace(sampling,
                                       seed=sampling.seed + i)

        trace_id = request.headers.get("x-request-id")
        subs = [await self.async_engine.submit(
            prompt, choice_sampling(i), lora_name=lora_name,
            request_id=trace_id, priority=int(priority),
            spec_off=spec_off)
            for i in range(candidates)]

        def legacy_lp(lps):
            """lp_json entries -> the legacy /v1/completions shape."""
            if not lps:
                return None
            return {
                "tokens": [e["token"] for e in lps],
                "token_logprobs": [e["logprob"] for e in lps],
                "top_logprobs": [
                    {t["token"]: t["logprob"]
                     for t in e["top_logprobs"]}
                    for e in lps],
            }

        def lp_json(token_id, entry):
            """One position in OpenAI chat logprobs.content form."""
            slp, tops = entry
            txt = self.tokenizer.decode([token_id])
            return {
                "token": txt, "logprob": slp,
                "bytes": list(txt.encode("utf-8", "replace")),
                "top_logprobs": [
                    {"token": self.tokenizer.decode([tid]),
                     "logprob": tlp}
                    for tid, tlp in tops
                ],
            }

        async def consume_choice(seq_id, stream, on_delta=None):
            """Drain one sequence's stream with stop-string scanning.

            Returns (text, n_tokens, finish_reason, lp_content);
            ``on_delta(text, lp_entries)`` (streaming mode) is awaited
            per emitted text delta — lp_entries carries the logprob
            positions consumed since the previous emit (the
            detokenizer may buffer partial UTF-8, so text deltas and
            token positions align only at emit points).

            Logprob entries are released by CHARACTER accounting: a
            token's entry joins logprobs.content only once its decoded
            text has fully left the stop-string hold-back buffer, so a
            stop hit drops the entries of every (partially) truncated
            token — held-back runs included — and the content list
            always aligns with the returned text.
            """
            decoder = self._delta_decoder()
            scanner = _StopStringScanner(sampling.stop_strings)
            pieces: List[str] = []
            lp_content: List[dict] = []
            lp_queue: List[tuple] = []  # (entry, fed-chars watermark)
            fed_chars = 0
            emitted_chars = 0
            n_tokens = 0
            finish_reason = "stop"

            def release_entries():
                ready = []
                while (lp_queue and lp_queue[0][1] is not None
                       and lp_queue[0][1] <= emitted_chars):
                    ready.append(lp_queue.pop(0)[0])
                lp_content.extend(ready)
                return ready

            def queue_entry(entry, token_text):
                # A token the detokenizer buffered (zero visible
                # chars) can't be char-aligned on its own: its bytes
                # surface inside a LATER feed's text, so it inherits
                # that feed's watermark.
                lp_queue.append(
                    [entry, fed_chars if token_text else None])

            def settle_watermarks():
                for item in lp_queue:
                    if item[1] is None:
                        item[1] = fed_chars

            async def emit(text):
                nonlocal emitted_chars
                emitted_chars += len(text)
                ready = release_entries()
                if not text and not ready:
                    return
                if on_delta is not None:
                    # Streaming: deltas go straight to the wire; never
                    # buffer the whole completion in memory.
                    await on_delta(text, ready)
                elif text:
                    pieces.append(text)

            try:
                while True:
                    out = await stream.get()
                    if out.new_token is not None:
                        n_tokens += 1
                        token_text = decoder(out.new_token)
                        fed_chars += len(token_text)
                        if token_text:
                            settle_watermarks()
                        if out.logprobs is not None:
                            queue_entry(
                                lp_json(out.new_token, out.logprobs),
                                token_text)
                        await emit(scanner.feed(token_text))
                        if scanner.stopped:
                            # Text-level stop hit: the engine doesn't
                            # know about it, so cut generation here.
                            self.async_engine.abort(seq_id)
                            finish_reason = "stop"
                            break
                    if out.finished:
                        finish_reason = out.finish_reason or "stop"
                        tail = decoder(None, flush=True)
                        fed_chars += len(tail)
                        settle_watermarks()
                        await emit(scanner.feed(tail))
                        await emit(scanner.flush())
                        if scanner.stopped:
                            # The stop landed in the final flush: the
                            # engine's reason (e.g. length) is
                            # superseded by the text-level stop.
                            finish_reason = "stop"
                        break
            finally:
                self.async_engine.finish_stream(seq_id)
            return ("".join(pieces), n_tokens, finish_reason,
                    lp_content)

        if not stream_mode:
            tasks = [asyncio.ensure_future(consume_choice(sid, stream))
                     for sid, stream in subs]
            try:
                results = await asyncio.gather(*tasks)
            except BaseException:
                # One choice failed or the request was cancelled:
                # cancel the sibling consumers (gather leaves them
                # running) and stop every engine sequence.
                for t in tasks:
                    t.cancel()
                for sid, _ in subs:
                    self.async_engine.abort(sid)
                await asyncio.gather(*tasks, return_exceptions=True)
                raise
            if candidates > n:
                # Rank by mean token logprob; ties keep earlier
                # candidates. The extra candidates' tokens still count
                # toward usage (they were generated).
                def mean_lp(r):
                    lps = r[3]
                    if not lps:
                        return float("-inf")
                    return (sum(e["logprob"] for e in lps)
                            / len(lps))
                ranked = sorted(range(candidates),
                                key=lambda i: -mean_lp(results[i]))
                total_tokens = sum(r[1] for r in results)
                results = [results[i] for i in ranked[:n]]
                if not requested_lp:
                    sampling = dataclasses.replace(
                        sampling, logprobs=False)
            else:
                total_tokens = sum(r[1] for r in results)
            if chat:
                choices = [{
                    "index": i,
                    "message": {"role": "assistant", "content": text},
                    "finish_reason": finish,
                    "logprobs": ({"content": lps}
                                 if sampling.logprobs else None),
                } for i, (text, _, finish, lps)
                  in enumerate(results)]
                payload = {
                    "id": rid, "object": "chat.completion",
                    "created": created, "model": response_model,
                    "choices": choices,
                    "usage": _usage(len(prompt), total_tokens),
                }
            else:
                choices = [{
                    "index": i, "text": echo_text + text,
                    "finish_reason": finish,
                    "logprobs": (legacy_lp(lps)
                                 if sampling.logprobs else None),
                } for i, (text, _, finish, lps)
                  in enumerate(results)]
                payload = {
                    "id": rid, "object": "text_completion",
                    "created": created, "model": response_model,
                    "choices": choices,
                    "usage": _usage(len(prompt), total_tokens),
                }
            return web.json_response(payload)

        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
        })
        await resp.prepare(request)

        def sse(payload: dict) -> bytes:
            return f"data: {json.dumps(payload)}\n\n".encode()

        def chunk(index: int, delta: Optional[str],
                  finish: Optional[str], first: bool = False,
                  lps=None) -> dict:
            if chat:
                d: Dict[str, Any] = {}
                if first:
                    d["role"] = "assistant"
                if delta:
                    d["content"] = delta
                choice = {"index": index, "delta": d,
                          "finish_reason": finish}
                if sampling.logprobs:
                    choice["logprobs"] = (
                        {"content": lps} if lps else None)
                obj = "chat.completion.chunk"
            else:
                choice = {"index": index, "text": delta or "",
                          "finish_reason": finish}
                if sampling.logprobs:
                    choice["logprobs"] = legacy_lp(lps)
                obj = "text_completion"
            return {"id": rid, "object": obj, "created": created,
                    "model": response_model, "choices": [choice]}

        write_lock = asyncio.Lock()
        completion_tokens = [0] * n
        # Mid-stream crash safety (docs/crash_recovery.md): single-
        # choice plain streams relay the engine's latest resume
        # descriptor as an SSE comment frame — invisible to SSE
        # clients, stripped and remembered by the router for a
        # /v1/resume re-submission if this process dies. Multi-choice,
        # logprobs and echo streams carry wire state one descriptor
        # cannot reconstruct, so they stream without a safety net.
        relay_ckpt = (self.engine.config.checkpoint_interval_tokens > 0
                      and candidates == 1 and not sampling.logprobs
                      and not echo)

        def ckpt_frame(ckpt: dict) -> bytes:
            desc = {
                "version": 1,
                "request_id": trace_id,
                "response_id": rid,
                "created": created,
                "chat": chat,
                "model": response_model,
                "kv_dtype":
                    self.engine.config.cache.resolved_kv_dtype(),
                "sampling": _sampling_to_wire(sampling),
            }
            desc.update(ckpt)
            return f": checkpoint {json.dumps(desc)}\n\n".encode()

        async def stream_choice(index, seq_id, stream):
            async def on_delta(text, lps):
                async with write_lock:
                    await resp.write(sse(chunk(index, text, None,
                                               lps=lps)))
                    if relay_ckpt:
                        ckpt = self.engine.take_checkpoint(seq_id)
                        if ckpt is not None:
                            await resp.write(ckpt_frame(ckpt))
                            if self.migrate_drain:
                                # Migrate-mode drain (docs/fleet.md):
                                # the frame just written is the full
                                # resume state, so cut the connection
                                # abruptly — a clean EOF would read as
                                # a finished stream, while an abrupt
                                # close makes the router resume it on
                                # another replica byte-exactly.
                                tracer = self.engine.tracer
                                if tracer is not None:
                                    tracer.event(seq_id, "migrate_ship")
                                # In-band marker: the router's config
                                # watcher polls too slowly to classify
                                # this cut as a migration on its own.
                                await resp.write(b": migrating\n\n")
                                if request.transport is not None:
                                    request.transport.close()

            _, n_toks, finish_reason, _ = await consume_choice(
                seq_id, stream, on_delta=on_delta)
            completion_tokens[index] = n_toks
            async with write_lock:
                await resp.write(sse(chunk(index, None, finish_reason)))

        tasks = [asyncio.ensure_future(stream_choice(i, sid, stream))
                 for i, (sid, stream) in enumerate(subs)]
        try:
            if chat:
                # Under the lock: the stream_choice tasks are already
                # scheduled, and a content delta must never overtake
                # its choice's role chunk.
                async with write_lock:
                    for i in range(n):
                        await resp.write(sse(chunk(i, None, None,
                                                   first=True)))
            elif echo_text:
                async with write_lock:
                    for i in range(n):
                        await resp.write(sse(chunk(i, echo_text,
                                                   None)))
            await asyncio.gather(*tasks)
            stream_opts = body.get("stream_options")
            if (isinstance(stream_opts, dict)
                    and stream_opts.get("include_usage")):
                # OpenAI stream_options.include_usage: one final chunk
                # with empty choices and the aggregate usage.
                await resp.write(sse({
                    "id": rid,
                    "object": ("chat.completion.chunk" if chat
                               else "text_completion"),
                    "created": created, "model": response_model,
                    "choices": [],
                    "usage": _usage(len(prompt),
                                    sum(completion_tokens)),
                }))
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
        except BaseException:
            # Disconnect or failure on one choice: cancel the sibling
            # stream tasks BEFORE aborting (abort pops their streams,
            # and a consumer still waiting on a popped stream would
            # block forever), then reap them.
            for t in tasks:
                t.cancel()
            for sid, _ in subs:
                self.async_engine.abort(sid)
            await asyncio.gather(*tasks, return_exceptions=True)
            raise
        return resp

    # -- disaggregated serving (docs/disaggregation.md) ---------------------

    async def disagg_prefill(self, request: web.Request):
        """POST /v1/disagg/prefill: run the prompt through the normal
        chunked-prefill path, ship the committed KV pages to the
        offload tiers (push-on-prefill-done) and return the handoff
        descriptor a decode-role engine resumes from. The first
        sampled token rides the descriptor — it is never recomputed.

        Any engine can serve this (the role gates routing, not
        capability); without an offload tier the descriptor ships zero
        pages and the decode side recomputes (degraded, still exact).
        """
        body = await self._json_body(request)
        messages = body.get("messages")
        chat = isinstance(messages, list)
        if chat:
            prompt = render_chat_prompt(
                self.tokenizer, messages,
                chat_template=self.chat_template)
        else:
            prompt_in = body.get("prompt", "")
            if (isinstance(prompt_in, list) and prompt_in
                    and isinstance(prompt_in[0], int)):
                prompt = list(prompt_in)
            elif isinstance(prompt_in, list):
                prompt = self.tokenizer.encode("".join(prompt_in))
            else:
                prompt = self.tokenizer.encode(str(prompt_in))
        try:
            sampling = _sampling_from_body(
                body, self.engine.config.scheduler.max_model_len,
                vocab_size=self.engine.config.model.vocab_size,
            )
        except (ValueError, TypeError) as e:
            return web.json_response(
                {"error": {"message": str(e),
                           "type": "invalid_request_error"}},
                status=400,
            )
        if (sampling.guided is not None or sampling.logprobs
                or body.get("model") in self.engine.lora_names()):
            # Monolithic-only features: guided automaton state and
            # first-token logprobs do not transfer across a handoff,
            # and adapter cache salts are process-local. The router
            # never disagg-routes these; a direct caller gets 400.
            return web.json_response(
                {"error": {"message": (
                    "request cannot be disaggregated (guided "
                    "decoding, logprobs and LoRA adapters are "
                    "monolithic-only)"),
                    "type": "invalid_request_error"}},
                status=400,
            )
        max_prompt = self.engine.config.scheduler.max_model_len - 1
        if len(prompt) > max_prompt:
            return web.json_response(
                {"error": {"message": (
                    f"Prompt is {len(prompt)} tokens; maximum is "
                    f"{max_prompt}"),
                    "type": "invalid_request_error"}},
                status=400,
            )
        seq_id, stream = await self.async_engine.submit(
            prompt, sampling, handoff_prefill=True,
            request_id=request.headers.get("x-request-id"))
        try:
            out = await stream.get()
        finally:
            self.async_engine.finish_stream(seq_id)
        if out.new_token is None and out.finish_reason == "abort":
            return web.json_response(
                {"error": {"message":
                           "prefill engine rejected the request"}},
                status=503, headers={"Retry-After": "1"},
            )
        info = (self.engine.take_handoff_info(seq_id)
                or {"num_pages": 0, "kv_bytes": 0, "page_keys": []})
        descriptor = {
            "version": 1,
            "request_id": seq_id,
            "chat": chat,
            "model": self.model_name,
            "token_ids": list(prompt),
            "first_token": out.new_token,
            # Non-None when the first token already finished the
            # request (stop/length): the decode side then emits that
            # single token and never touches its engine.
            "finish_reason": (out.finish_reason
                              if out.finish_reason != "handoff"
                              else None),
            "kv_dtype": self.engine.config.cache.resolved_kv_dtype(),
            "page_keys": info["page_keys"],
            "num_pages": info["num_pages"],
            "kv_bytes": info["kv_bytes"],
            "sampling": _sampling_to_wire(sampling),
        }
        return web.json_response({"descriptor": descriptor})

    async def disagg_handoff(self, request: web.Request):
        """POST /v1/disagg/handoff: resume decoding from a prefill
        engine's descriptor. Emits OpenAI chunks (or one JSON
        completion), starting with the descriptor's first sampled
        token; the engine restores the shipped KV pages (AWAITING_KV)
        or degrades to recompute — the request always completes."""
        body = await self._json_body(request)
        desc = body.get("descriptor")
        if not isinstance(desc, dict):
            return web.json_response(
                {"error": {"message": "'descriptor' object is "
                                      "required"}}, status=400)
        token_ids = desc.get("token_ids")
        first_token = desc.get("first_token")
        if (not isinstance(token_ids, list)
                or not all(isinstance(t, int) for t in token_ids)
                or not isinstance(first_token, int)):
            return web.json_response(
                {"error": {"message": "descriptor missing "
                                      "token_ids/first_token"}},
                status=400)
        my_dtype = self.engine.config.cache.resolved_kv_dtype()
        desc_dtype = desc.get("kv_dtype") or my_dtype
        if desc_dtype != my_dtype:
            # 409: this pod can NEVER restore those pages (tier keys
            # are dtype-namespaced) — the router stops retrying the
            # decode pool and falls back to a monolithic recompute.
            return web.json_response(
                {"error": {"message": (
                    f"handoff KV not restorable here (descriptor "
                    f"kv_dtype {desc_dtype!r}, engine "
                    f"{my_dtype!r})")}},
                status=409)
        try:
            sampling = _sampling_from_wire(desc.get("sampling") or {})
        except Exception as e:
            return web.json_response(
                {"error": {"message":
                           f"bad descriptor sampling: {e}"}},
                status=400)
        chat = bool(desc.get("chat", True))
        stream_mode = bool(body.get("stream", False))
        created = int(time.time())
        rid = (("chatcmpl-" if chat else "cmpl-")
               + uuid.uuid4().hex[:16])
        finish_hint = desc.get("finish_reason")
        seq_id: Optional[str] = None
        stream: Optional[asyncio.Queue] = None
        if not finish_hint and sampling.max_tokens > 1:
            seq_id, stream = await self.async_engine.submit_handoff(
                token_ids, first_token, sampling,
                request_id=request.headers.get("x-request-id"))
        # Peek the first engine event so a rejected submission (queue
        # full) surfaces as a retryable 503, not a stream that aborts
        # after the headers already went out.
        first_out = None
        if stream is not None:
            first_out = await stream.get()
            if (first_out.finished and first_out.new_token is None
                    and first_out.finish_reason == "abort"):
                self.async_engine.finish_stream(seq_id)
                return web.json_response(
                    {"error": {"message":
                               "decode engine rejected the handoff"}},
                    status=503, headers={"Retry-After": "1"},
                )

        async def produce(on_text):
            """Decode + stop-scan the token stream (first token from
            the descriptor, rest from the engine); returns
            (completion_tokens, finish_reason)."""
            decoder = self._delta_decoder()
            scanner = _StopStringScanner(sampling.stop_strings)
            n_tokens = 1
            try:
                await on_text(scanner.feed(decoder(first_token)))
                if scanner.stopped:
                    if seq_id is not None:
                        self.async_engine.abort(seq_id)
                    return n_tokens, "stop"
                if stream is None:
                    tail = scanner.feed(decoder(None, flush=True))
                    await on_text(tail + scanner.flush())
                    return n_tokens, finish_hint or "length"
                out = first_out
                while True:
                    if out.new_token is not None:
                        n_tokens += 1
                        await on_text(
                            scanner.feed(decoder(out.new_token)))
                        if scanner.stopped:
                            self.async_engine.abort(seq_id)
                            return n_tokens, "stop"
                    if out.finished:
                        finish = out.finish_reason or "stop"
                        tail = scanner.feed(decoder(None, flush=True))
                        await on_text(tail + scanner.flush())
                        return (n_tokens,
                                "stop" if scanner.stopped else finish)
                    out = await stream.get()
            finally:
                if seq_id is not None:
                    self.async_engine.finish_stream(seq_id)

        if not stream_mode:
            pieces: List[str] = []

            async def collect(t):
                if t:
                    pieces.append(t)

            try:
                n_tokens, finish = await produce(collect)
            except BaseException:
                if seq_id is not None:
                    self.async_engine.abort(seq_id)
                raise
            text = "".join(pieces)
            if chat:
                choice = {"index": 0,
                          "message": {"role": "assistant",
                                      "content": text},
                          "finish_reason": finish}
                obj = "chat.completion"
            else:
                choice = {"index": 0, "text": text,
                          "finish_reason": finish}
                obj = "text_completion"
            return web.json_response({
                "id": rid, "object": obj, "created": created,
                "model": self.model_name, "choices": [choice],
                "usage": _usage(len(token_ids), n_tokens),
            })

        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
        })
        await resp.prepare(request)

        def sse(payload: dict) -> bytes:
            return f"data: {json.dumps(payload)}\n\n".encode()

        def chunk(delta: Optional[str], finish: Optional[str],
                  first: bool = False) -> dict:
            if chat:
                d: Dict[str, Any] = {}
                if first:
                    d["role"] = "assistant"
                if delta:
                    d["content"] = delta
                choice = {"index": 0, "delta": d,
                          "finish_reason": finish}
                obj = "chat.completion.chunk"
            else:
                choice = {"index": 0, "text": delta or "",
                          "finish_reason": finish}
                obj = "text_completion"
            return {"id": rid, "object": obj, "created": created,
                    "model": self.model_name, "choices": [choice]}

        async def emit(t):
            if t:
                await resp.write(sse(chunk(t, None)))

        try:
            if chat:
                await resp.write(sse(chunk(None, None, first=True)))
            _, finish = await produce(emit)
            await resp.write(sse(chunk(None, finish)))
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
        except BaseException:
            if seq_id is not None:
                self.async_engine.abort(seq_id)
            raise
        return resp

    async def resume(self, request: web.Request):
        """POST /v1/resume: continue a stream whose engine died
        mid-generation (docs/crash_recovery.md). The body carries the
        checkpoint descriptor the dead engine attached to its SSE
        stream plus ``delivered_text_chars`` — how much content text
        the router already forwarded to the client. The journaled
        context parks in ``AWAITING_KV`` (restore the checkpointed
        pages, or recompute from the token journal on a miss); the
        handler replays the journal through the same detokenizer +
        stop-scanner pipeline the dead engine ran, skips the
        already-delivered characters, and streams the rest — for
        greedy sampling the concatenated client stream is
        byte-identical to an uninterrupted run."""
        body = await self._json_body(request)
        desc = body.get("descriptor")
        if not isinstance(desc, dict):
            return web.json_response(
                {"error": {"message": "'descriptor' object is "
                                      "required"}}, status=400)
        token_ids = desc.get("tokens")
        output_tokens = desc.get("output_tokens")
        if (not isinstance(token_ids, list) or not token_ids
                or not all(isinstance(t, int) for t in token_ids)
                or not isinstance(output_tokens, int)
                or not 0 < output_tokens < len(token_ids)):
            return web.json_response(
                {"error": {"message": "descriptor missing "
                                      "tokens/output_tokens"}},
                status=400)
        my_dtype = self.engine.config.cache.resolved_kv_dtype()
        desc_dtype = desc.get("kv_dtype") or my_dtype
        if desc_dtype != my_dtype:
            # 409: this pod can NEVER restore those pages (tier keys
            # are dtype-namespaced) — the router must pick a
            # same-dtype replacement or accept a recompute elsewhere.
            return web.json_response(
                {"error": {"message": (
                    f"checkpoint KV not restorable here (descriptor "
                    f"kv_dtype {desc_dtype!r}, engine "
                    f"{my_dtype!r})")}},
                status=409)
        try:
            sampling = _sampling_from_wire(desc.get("sampling") or {})
        except Exception as e:
            return web.json_response(
                {"error": {"message":
                           f"bad descriptor sampling: {e}"}},
                status=400)
        if sampling.guided is not None:
            return web.json_response(
                {"error": {"message": "guided streams cannot be "
                                      "resumed"}}, status=400)
        try:
            delivered = int(body.get("delivered_text_chars") or 0)
        except (TypeError, ValueError):
            delivered = -1
        if delivered < 0:
            return web.json_response(
                {"error": {"message": "delivered_text_chars must be "
                                      "a non-negative integer"}},
                status=400)
        chat = bool(desc.get("chat", True))
        stream_mode = bool(body.get("stream", True))
        # The original stream's identity: resumed chunks must carry
        # the SAME id/created/model for the concatenated stream to be
        # byte-identical to an uninterrupted run.
        rid = (desc.get("response_id")
               or ("chatcmpl-" if chat else "cmpl-")
               + uuid.uuid4().hex[:16])
        created = int(desc.get("created") or time.time())
        response_model = desc.get("model") or self.model_name
        prompt_len = len(token_ids) - output_tokens
        output_ids = token_ids[prompt_len:]

        seq_id, stream = await self.async_engine.submit_resume(
            token_ids, output_tokens, sampling,
            request_id=request.headers.get("x-request-id"))
        # Peek the first engine event so a rejected submission (queue
        # full / draining race) surfaces as a retryable 503, not a
        # stream that aborts after the headers went out.
        first_out = await stream.get()
        if (first_out.finished and first_out.new_token is None
                and first_out.finish_reason == "abort"):
            self.async_engine.finish_stream(seq_id)
            return web.json_response(
                {"error": {"message":
                           "engine rejected the resume"}},
                status=503, headers={"Retry-After": "1"},
            )

        async def produce(on_text):
            """Replay the journal through a fresh detokenizer + stop
            scanner (rebuilding the dead engine's exact text state),
            skip the already-delivered chars, then stream new tokens.
            Returns (completion_tokens, finish_reason)."""
            decoder = self._delta_decoder()
            scanner = _StopStringScanner(sampling.stop_strings)
            n_tokens = output_tokens
            skip = delivered

            async def put(text):
                nonlocal skip
                if not text:
                    return
                if skip:
                    if len(text) <= skip:
                        skip -= len(text)
                        return
                    text = text[skip:]
                    skip = 0
                await on_text(text)

            try:
                for tok in output_ids:
                    await put(scanner.feed(decoder(tok)))
                    if scanner.stopped:
                        self.async_engine.abort(seq_id)
                        return n_tokens, "stop"
                out = first_out
                while True:
                    if out.new_token is not None:
                        n_tokens += 1
                        await put(scanner.feed(decoder(out.new_token)))
                        if scanner.stopped:
                            self.async_engine.abort(seq_id)
                            return n_tokens, "stop"
                    if out.finished:
                        finish = out.finish_reason or "stop"
                        tail = scanner.feed(decoder(None, flush=True))
                        await put(tail + scanner.flush())
                        return (n_tokens,
                                "stop" if scanner.stopped else finish)
                    out = await stream.get()
            finally:
                self.async_engine.finish_stream(seq_id)

        if not stream_mode:
            pieces: List[str] = []

            async def collect(t):
                if t:
                    pieces.append(t)

            try:
                n_tokens, finish = await produce(collect)
            except BaseException:
                self.async_engine.abort(seq_id)
                raise
            text = "".join(pieces)
            if chat:
                choice = {"index": 0,
                          "message": {"role": "assistant",
                                      "content": text},
                          "finish_reason": finish}
                obj = "chat.completion"
            else:
                choice = {"index": 0, "text": text,
                          "finish_reason": finish}
                obj = "text_completion"
            return web.json_response({
                "id": rid, "object": obj, "created": created,
                "model": response_model, "choices": [choice],
                "usage": _usage(prompt_len, n_tokens),
            })

        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
        })
        await resp.prepare(request)

        def sse(payload: dict) -> bytes:
            return f"data: {json.dumps(payload)}\n\n".encode()

        def chunk(delta: Optional[str],
                  finish: Optional[str]) -> dict:
            # Shape-identical to the monolithic stream's chunk() (no
            # role chunk — the dead engine already delivered it).
            if chat:
                d: Dict[str, Any] = {}
                if delta:
                    d["content"] = delta
                choice = {"index": 0, "delta": d,
                          "finish_reason": finish}
                obj = "chat.completion.chunk"
            else:
                choice = {"index": 0, "text": delta or "",
                          "finish_reason": finish}
                obj = "text_completion"
            return {"id": rid, "object": obj, "created": created,
                    "model": response_model, "choices": [choice]}

        def ckpt_frame(ckpt: dict) -> bytes:
            # Keep checkpointing on the resumed leg too, so a second
            # crash resumes again (the descriptor identity fields are
            # carried forward from the original stream).
            new_desc = {
                "version": 1,
                "request_id": desc.get("request_id"),
                "response_id": rid,
                "created": created,
                "chat": chat,
                "model": response_model,
                "kv_dtype": my_dtype,
                "sampling": _sampling_to_wire(sampling),
            }
            new_desc.update(ckpt)
            return f": checkpoint {json.dumps(new_desc)}\n\n".encode()

        async def emit(t):
            if t:
                await resp.write(sse(chunk(t, None)))
            ckpt = self.engine.take_checkpoint(seq_id)
            if ckpt is not None:
                await resp.write(ckpt_frame(ckpt))
                if self.migrate_drain:
                    # Migrate-mode drain cuts resumed legs too — a
                    # stream can hop replicas more than once during a
                    # rolling upgrade (docs/fleet.md).
                    tracer = self.engine.tracer
                    if tracer is not None:
                        tracer.event(seq_id, "migrate_ship")
                    # Same in-band migration marker as the original
                    # stream leg.
                    await resp.write(b": migrating\n\n")
                    if request.transport is not None:
                        request.transport.close()

        try:
            _, finish = await produce(emit)
            await resp.write(sse(chunk(None, finish)))
            await resp.write(b"data: [DONE]\n\n")
            await resp.write_eof()
        except BaseException:
            self.async_engine.abort(seq_id)
            raise
        return resp

    async def embeddings(self, request: web.Request):
        """OpenAI /v1/embeddings over the served model's hidden states."""
        from production_stack_tpu.engine.embeddings import (
            parse_embedding_input,
        )
        body = await self._json_body(request)
        try:
            token_lists = parse_embedding_input(
                body.get("input"), self.tokenizer,
                max_len=self.engine.config.scheduler.max_model_len,
            )
        except ValueError as e:
            return web.json_response(
                {"error": {"message": str(e),
                           "type": "invalid_request_error"}},
                status=400,
            )
        try:
            await self._ensure_embedder()
        except NotImplementedError as e:
            return web.json_response(
                {"error": {"message": str(e)}}, status=501,
            )
        # One embed batch on-device at a time; compute off the event
        # loop so token streaming stays live.
        async with self._embed_lock:
            vectors = await asyncio.to_thread(
                self._embedder.embed_batch, token_lists
            )
        n_tokens = sum(len(t) for t in token_lists)
        return web.json_response({
            "object": "list",
            "model": self.model_name,
            "data": [
                {"object": "embedding", "index": i,
                 "embedding": vec.tolist()}
                for i, vec in enumerate(vectors)
            ],
            "usage": {"prompt_tokens": n_tokens,
                      "total_tokens": n_tokens},
        })

    async def _ensure_embedder(self):
        from production_stack_tpu.engine.embeddings import Embedder
        if self._embedder is None:
            if self.engine.runner.bridge is not None:
                # Multihost: lazy construction would launch a
                # collective program workers never mirror (they only
                # enter embedders built at startup by main()), so the
                # slice would deadlock on the first request.
                raise NotImplementedError(
                    "embeddings unavailable: this multihost slice was "
                    "started without an embedder (unsupported "
                    "architecture or quantized weights)"
                )
            self._embedder = Embedder(
                self.engine.config.model,
                self.engine.runner.params,
                max_len=self.engine.config.scheduler.max_model_len,
                pooling=self.pooling,
            )
            self.engine.runner.embedder = self._embedder
        return self._embedder

    async def _pair_scores(self, query: str, documents: List[str]):
        """Bi-encoder relevance: cosine of pooled embeddings (the
        engine-side backend for the router's /score and /rerank proxy
        paths, reference main_router.py:42-84)."""
        import numpy as np
        embedder = await self._ensure_embedder()
        max_len = self.engine.config.scheduler.max_model_len
        token_lists = [self.tokenizer.encode(query)[:max_len]] + [
            self.tokenizer.encode(d)[:max_len] for d in documents
        ]
        for ids in token_lists:
            if not ids:
                raise ValueError("texts must not be empty")
        async with self._embed_lock:
            vectors = await asyncio.to_thread(
                embedder.embed_batch, token_lists
            )
        q_vec, d_vecs = vectors[0], vectors[1:]
        # Embeddings are L2-normalized: dot == cosine.
        scores = d_vecs @ q_vec
        n_tokens = sum(len(t) for t in token_lists)
        return [float(s) for s in scores], n_tokens

    async def score(self, request: web.Request):
        """/v1/score: relevance of text_2 document(s) to text_1."""
        body = await self._json_body(request)
        text_1 = body.get("text_1") or body.get("query")
        text_2 = body.get("text_2") or body.get("documents")
        if not isinstance(text_1, str) or text_2 is None:
            return web.json_response(
                {"error": {"message": "'text_1' (string) and 'text_2' "
                                      "(string or list) are required"}},
                status=400,
            )
        docs = [text_2] if isinstance(text_2, str) else list(text_2)
        try:
            scores, n_tokens = await self._pair_scores(text_1, docs)
        except ValueError as e:
            return web.json_response(
                {"error": {"message": str(e)}}, status=400)
        except NotImplementedError as e:
            return web.json_response(
                {"error": {"message": str(e)}}, status=501)
        return web.json_response({
            "id": "score-" + uuid.uuid4().hex[:16],
            "object": "list",
            "model": self.model_name,
            "data": [
                {"object": "score", "index": i, "score": s}
                for i, s in enumerate(scores)
            ],
            "usage": {"prompt_tokens": n_tokens,
                      "total_tokens": n_tokens},
        })

    async def rerank(self, request: web.Request):
        """/v1/rerank: order documents by relevance to the query."""
        body = await self._json_body(request)
        query = body.get("query")
        documents = body.get("documents")
        if not isinstance(query, str) or not isinstance(documents, list):
            return web.json_response(
                {"error": {"message": "'query' (string) and 'documents'"
                                      " (list of strings) are required"}},
                status=400,
            )
        try:
            scores, n_tokens = await self._pair_scores(
                query, [str(d) for d in documents])
        except ValueError as e:
            return web.json_response(
                {"error": {"message": str(e)}}, status=400)
        except NotImplementedError as e:
            return web.json_response(
                {"error": {"message": str(e)}}, status=501)
        order = sorted(range(len(scores)), key=lambda i: -scores[i])
        top_n = body.get("top_n")
        if isinstance(top_n, int) and top_n > 0:
            order = order[:top_n]
        return web.json_response({
            "id": "rerank-" + uuid.uuid4().hex[:16],
            "model": self.model_name,
            "usage": {"total_tokens": n_tokens},
            "results": [
                {
                    "index": i,
                    "document": {"text": documents[i]},
                    "relevance_score": scores[i],
                }
                for i in order
            ],
        })

    async def models(self, request: web.Request):
        created = int(self.async_engine.uptime_start)
        data = [{
            "id": self.model_name, "object": "model",
            "created": created,
            "owned_by": "production-stack-tpu",
        }]
        # LoRA adapters are addressable models (vLLM behavior).
        for name in self.engine.lora_names():
            data.append({
                "id": name, "object": "model", "created": created,
                "owned_by": "production-stack-tpu",
                "parent": self.model_name,
            })
        return web.json_response({"object": "list", "data": data})

    async def health(self, request: web.Request):
        # ``role`` feeds the router's role-aware discovery
        # (router/service_discovery.py probes it; absent on older
        # engines -> treated as "both"). ``draining`` makes the active
        # health prober fail the endpoint out of routing while its
        # in-flight streams finish (docs/fleet.md); the fleet manager
        # polls ``active_requests`` to know when a SIGTERM is loss-free.
        # getattr: older configs (and test stubs) predate the watchdog.
        wd = getattr(self.engine.config, "step_watchdog_s", 0.0)
        if wd > 0:
            stuck = self.async_engine.current_step_s()
            if stuck > wd:
                # A wedged device step stalls every queued request; a
                # 503 makes the router's prober rotate the replica out
                # (docs/crash_recovery.md).
                self._note_watchdog_trip(stuck)
                return web.json_response({
                    "status": "watchdog",
                    "stuck_step_s": round(stuck, 3),
                    "role": self.engine.config.engine_role,
                    "draining": self.draining,
                    "active_requests": self._active_generations,
                    "build_id": self.build_id,
                }, status=503)
            self._watchdog_tripped = False
        return web.json_response({
            "status": "ok",
            "role": self.engine.config.engine_role,
            "draining": self.draining,
            "active_requests": self._active_generations,
            "build_id": self.build_id,
        })

    def _note_watchdog_trip(self, stuck: float) -> None:
        if self._watchdog_tripped:
            return
        self._watchdog_tripped = True
        logger.error("Step watchdog tripped: step running for %.3fs "
                     "(limit %.3fs); /health now 503",
                     stuck, self.engine.config.step_watchdog_s)
        tracer = self.engine.tracer
        if tracer is not None:
            # Synthetic span (profiler-capture pattern) so the trip is
            # visible in traceview next to the requests it stalled.
            sid = f"watchdog-{uuid.uuid4().hex[:12]}"
            tracer.start(sid, prompt_tokens=0)
            tracer.event(sid, "watchdog_trip", step_s=round(stuck, 3))
            tracer.finish(sid, reason="watchdog")

    # -- zero-loss drain (docs/fleet.md) ------------------------------------

    def _drain_rejection(self) -> Optional[web.Response]:
        if not self.draining:
            return None
        return web.json_response(
            {"error": {"message": "engine is draining; retry on "
                                  "another replica"}},
            status=503, headers={"Retry-After": "1"},
        )

    def _guarded(self, handler):
        """Wrap a generation handler: reject while draining, count the
        request as in-flight otherwise. The counter — not the engine's
        queue depth alone — gates drain-exit, because a stream keeps
        writing after its last engine step."""
        async def wrapped(request: web.Request):
            rejection = self._drain_rejection()
            if rejection is not None:
                return rejection
            self._active_generations += 1
            try:
                return await handler(request)
            finally:
                self._active_generations -= 1
        return wrapped

    async def drain(self, request: web.Request):
        """POST /drain: flip to DRAINING. New admissions are rejected
        with 503+Retry-After (the router retries them on another
        replica); everything already admitted finishes normally. With
        ``{"exit": true}`` the process exits clean once idle — the path
        the fleet manager uses so it never has to SIGKILL an engine
        that still has running sequences."""
        body: dict = {}
        if request.can_read_body:
            try:
                body = await request.json()
            except Exception:
                body = {}
        already = self.draining
        self.draining = True
        if body.get("migrate"):
            # Migrate-mode drain (docs/fleet.md): cut checkpointed
            # streams at their next checkpoint frame so the router
            # resumes them elsewhere instead of waiting them out.
            self.migrate_drain = True
        if not already:
            logger.info("Drain requested: rejecting new admissions, "
                        "%d generation request(s) in flight",
                        self._active_generations)
        if body.get("exit") and self._drain_exit_task is None:
            self._drain_exit_task = asyncio.ensure_future(
                self._exit_when_idle())
        stats = self.engine.stats()
        return web.json_response({
            "status": "draining",
            "active_requests": self._active_generations,
            "running": stats["num_requests_running"],
            "waiting": stats["num_requests_waiting"],
        })

    async def _exit_when_idle(self) -> None:
        """Wait for every in-flight generation to finish, then stop the
        process via SIGTERM (aiohttp's run_app shuts down gracefully on
        it). --drain-exit-timeout-s bounds the wait; 0 waits forever —
        the fleet manager applies its own deadline instead."""
        import os
        import signal
        deadline = (time.time() + self.drain_exit_timeout_s
                    if self.drain_exit_timeout_s > 0 else None)
        while (self._active_generations > 0
               or self.engine.has_work()):
            if deadline is not None and time.time() >= deadline:
                logger.warning(
                    "Drain exit timeout (%.1fs) with %d request(s) "
                    "still in flight; exiting anyway",
                    self.drain_exit_timeout_s, self._active_generations)
                break
            await asyncio.sleep(0.05)
        logger.info("Drain complete; exiting")
        os.kill(os.getpid(), signal.SIGTERM)

    async def profiler_start(self, request: web.Request):
        """Start a JAX profiler trace (view in TensorBoard/XProf).

        SURVEY.md §5: the reference has no tracing subsystem; the TPU
        engine adds profiler hooks as the aux-parity extension.
        """
        import jax
        trace_dir = request.query.get(
            "dir", self.profile_dir or "/tmp/jax-trace")
        if self._profiling:
            return web.json_response(
                {"error": {"message": "profiler already running"}},
                status=409,
            )
        jax.profiler.start_trace(trace_dir)
        self._profiling = True
        tracer = self.engine.tracer
        if tracer is not None:
            sid = f"prof-{uuid.uuid4().hex[:12]}"
            self._profiler_span_id = sid
            tracer.start(
                sid,
                request_id=request.headers.get("x-request-id"),
                prompt_tokens=0)
            tracer.event(sid, "profiler_start", dir=trace_dir)
        return web.json_response({"status": "started",
                                  "dir": trace_dir})

    async def profiler_stop(self, request: web.Request):
        import jax
        if not self._profiling:
            return web.json_response(
                {"error": {"message": "profiler not running"}},
                status=409,
            )
        jax.profiler.stop_trace()
        self._profiling = False
        tracer = self.engine.tracer
        sid, self._profiler_span_id = self._profiler_span_id, None
        if tracer is not None and sid is not None:
            tracer.event(sid, "profiler_stop")
            tracer.finish(sid, reason="profiler",
                          arrival_ts=time.time())
        return web.json_response({"status": "stopped"})

    async def debug_trace(self, request: web.Request):
        """GET /debug/trace/{request_id}: the flight recorder's event
        timeline for one request, looked up by router x-request-id or
        engine seq id (docs/observability.md)."""
        tracer = self.engine.tracer
        if tracer is None:
            return web.json_response(
                {"error": {"message": "tracing disabled"}}, status=404)
        found = tracer.lookup(request.match_info["request_id"])
        if found is None:
            return web.json_response(
                {"error": {"message": "no trace for that id (expired "
                                      "from the ring or never seen)"}},
                status=404)
        return web.json_response(found)

    async def debug_steps(self, request: web.Request):
        """GET /debug/steps[?limit=N]: most recent per-step flight
        recorder records, oldest first."""
        tracer = self.engine.tracer
        if tracer is None:
            return web.json_response(
                {"error": {"message": "tracing disabled"}}, status=404)
        try:
            limit = int(request.query.get("limit", "100"))
        except ValueError:
            return web.json_response(
                {"error": {"message": "limit must be an integer"}},
                status=400)
        return web.json_response(
            {"steps": tracer.recent_steps(limit=limit)})

    async def debug_compiles(self, request: web.Request):
        """GET /debug/compiles[?limit=N]: the device performance
        observatory's compile ledger — per-kind event/seconds
        counters, live executable-cache sizes, the bounded ring of
        recent compiles with their (rows, W) shape keys, and the
        PSTPU_TIMING dispatch aggregates (docs/observability.md)."""
        obs = getattr(self.engine.runner, "observatory", None)
        if obs is None:
            return web.json_response(
                {"error": {"message": "observatory disabled"}},
                status=404)
        try:
            limit = int(request.query.get("limit", "32"))
        except ValueError:
            return web.json_response(
                {"error": {"message": "limit must be an integer"}},
                status=400)
        return web.json_response(obs.compile_report(limit=limit))

    async def debug_memory(self, request: web.Request):
        """GET /debug/memory: the observatory's HBM ledger — analytic
        per-category breakdown (always available) plus
        device.memory_stats() where the backend supports it."""
        obs = getattr(self.engine.runner, "observatory", None)
        if obs is None:
            return web.json_response(
                {"error": {"message": "observatory disabled"}},
                status=404)
        return web.json_response(obs.memory_report())

    async def version(self, request: web.Request):
        return web.json_response({"version": __version__,
                                  "build_id": self.build_id})

    async def kv_summary_handler(self, request: web.Request):
        """Cluster KV economy (docs/kv_economy.md): the engine's live
        KV state for the router's KVStateAwarePolicy — hot prefix
        chains (text-domain blake2b, decayed hit counts), free-page
        headroom, and the KV storage dtype. Served from host-side
        tracker/counter state only; never touches the device."""
        cm = self.engine.cache_manager
        return web.json_response({
            "hot_chains": [[h, v]
                           for h, v in self.kv_summary.snapshot()],
            "free_pages": cm.num_free_pages,
            "total_pages": cm.config.num_pages - 1,
            "kv_dtype": self.engine.config.cache.resolved_kv_dtype(),
            "top_k": self.kv_summary.top_k,
        })

    async def metrics(self, request: web.Request):
        stats = self.engine.stats()
        lines = []
        for name, value in (
            ("vllm:num_requests_running",
             stats["num_requests_running"]),
            ("vllm:num_requests_waiting",
             stats["num_requests_waiting"]),
            ("vllm:gpu_cache_usage_perc",
             stats["gpu_cache_usage_perc"]),
            ("vllm:gpu_prefix_cache_hit_rate",
             stats["gpu_prefix_cache_hit_rate"]),
        ):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {float(value)}")
        lines.append("# TYPE vllm:num_preemptions_total counter")
        lines.append("vllm:num_preemptions_total "
                     f"{float(stats['num_preemptions_total'])}")
        # KV quantization telemetry: page budget after any int8
        # expansion, worst-case KV bytes written per decode step, and
        # the storage dtype as a labeled one-hot gauge so dashboards
        # can group pods by KV format.
        lines.append("# TYPE vllm:engine_kv_cache_page_capacity gauge")
        lines.append("vllm:engine_kv_cache_page_capacity "
                     f"{float(stats['engine_kv_cache_page_capacity'])}")
        lines.append("# TYPE vllm:engine_kv_bytes_per_decode_step gauge")
        lines.append(
            "vllm:engine_kv_bytes_per_decode_step "
            f"{float(stats['engine_kv_bytes_per_decode_step'])}")
        kv_dtype = self.engine.config.cache.resolved_kv_dtype()
        lines.append("# TYPE vllm:engine_kv_cache_dtype gauge")
        lines.append("vllm:engine_kv_cache_dtype{kv_dtype=\""
                     f"{kv_dtype}\"}} 1.0")
        # Disaggregated serving (docs/disaggregation.md): per-role
        # request counters, KV bytes shipped on handoffs, and the
        # AWAITING_KV admission depth.
        lines.append("# TYPE vllm:disagg_prefill_requests_total "
                     "counter")
        lines.append("vllm:disagg_prefill_requests_total "
                     f"{float(stats['disagg_prefill_requests_total'])}")
        lines.append("# TYPE vllm:disagg_decode_requests_total "
                     "counter")
        lines.append("vllm:disagg_decode_requests_total "
                     f"{float(stats['disagg_decode_requests_total'])}")
        lines.append("# TYPE vllm:disagg_kv_bytes_shipped_total "
                     "counter")
        lines.append("vllm:disagg_kv_bytes_shipped_total "
                     f"{float(stats['disagg_kv_bytes_shipped_total'])}")
        lines.append("# TYPE vllm:disagg_awaiting_kv_requests gauge")
        lines.append("vllm:disagg_awaiting_kv_requests "
                     f"{float(stats['disagg_awaiting_kv_requests'])}")
        # Cluster KV economy (docs/kv_economy.md): summary breadth and
        # headroom mirror GET /kv/summary; the cluster counters come
        # from the remote-tier client (0 until an offload remote is
        # configured — the scrape surface stays stable either way).
        cm = self.engine.cache_manager
        lines.append("# TYPE vllm:kv_summary_hot_chains gauge")
        lines.append("vllm:kv_summary_hot_chains "
                     f"{float(self.kv_summary.hot_count())}")
        lines.append("# TYPE vllm:kv_free_page_headroom gauge")
        lines.append("vllm:kv_free_page_headroom "
                     f"{float(cm.num_free_pages)}")
        lines.append("# TYPE vllm:kv_total_pages gauge")
        lines.append("vllm:kv_total_pages "
                     f"{float(cm.config.num_pages - 1)}")
        ostats = (self.engine.offload.stats()
                  if self.engine.offload is not None else {})
        lines.append("# TYPE vllm:kv_cluster_hits_total counter")
        lines.append("vllm:kv_cluster_hits_total "
                     f"{float(ostats.get('cluster_hits', 0.0))}")
        lines.append("# TYPE vllm:kv_cluster_misses_total counter")
        lines.append("vllm:kv_cluster_misses_total "
                     f"{float(ostats.get('cluster_misses', 0.0))}")
        lines.append("# TYPE vllm:kv_cluster_admissions_total counter")
        lines.append("vllm:kv_cluster_admissions_total "
                     f"{float(ostats.get('cluster_admissions', 0.0))}")
        lines.append("# TYPE vllm:kv_cluster_rejections_total counter")
        lines.append("vllm:kv_cluster_rejections_total "
                     f"{float(ostats.get('cluster_rejections', 0.0))}")
        # Zero-loss drain (docs/fleet.md): 1 while new admissions are
        # rejected and in-flight sequences finish.
        lines.append("# TYPE vllm:engine_draining gauge")
        lines.append(f"vllm:engine_draining {float(self.draining)}")
        # Self-tuning (docs/autotuning.md): controllers allowed to
        # act, latched guardrail freezes, live knob values, and
        # cumulative decision counts (applied + shadow).
        at = self.autotuner
        lines.append("# TYPE vllm:autotune_active_controllers gauge")
        lines.append("vllm:autotune_active_controllers "
                     f"{float(at.active_count())}")
        lines.append("# TYPE vllm:autotune_frozen gauge")
        for name, frozen in sorted(at.frozen_flags().items()):
            lines.append("vllm:autotune_frozen{controller=\""
                         f"{name}\"}} {float(frozen)}")
        lines.append("# TYPE vllm:autotune_knob_value gauge")
        for name, value in sorted(at.knob_values().items()):
            lines.append("vllm:autotune_knob_value{controller=\""
                         f"{name}\"}} {float(value)}")
        lines.append("# TYPE vllm:autotune_decisions_total counter")
        for name, count in sorted(at.decisions_total.items()):
            lines.append("vllm:autotune_decisions_total{controller=\""
                         f"{name}\"}} {float(count)}")
        # QoS under overload (docs/qos.md): per-class shed counts from
        # the 429 gate and per-outcome preemption counts (did the
        # victim's KV pages ship to the offload tier, or will the
        # victim recompute from scratch?).
        lines.append("# TYPE vllm:qos_shed_total counter")
        for cls, count in sorted(self.qos_shed_counts.items()):
            lines.append("vllm:qos_shed_total{class=\""
                         f"{cls}\"}} {float(count)}")
        lines.append("# TYPE vllm:preempt_offload_total counter")
        for outcome, count in sorted(
                self.engine.scheduler.preempt_offload_outcomes.items()):
            lines.append("vllm:preempt_offload_total{outcome=\""
                         f"{outcome}\"}} {float(count)}")
        # Device performance observatory (docs/observability.md):
        # compile ledger, HBM breakdown, step-time/MFU, and the
        # resolved attention impls as a labeled one-hot info gauge
        # (the silent-XLA-fallback alarm).
        obs = getattr(self.engine.runner, "observatory", None)
        if obs is not None:
            lines.append("# TYPE vllm:engine_compile_events_total "
                         "counter")
            for kind, count in sorted(
                    obs.compile_events_by_kind().items()):
                lines.append(
                    "vllm:engine_compile_events_total{kind=\""
                    f"{kind}\"}} {float(count)}")
            lines.append("# TYPE vllm:engine_compile_seconds_total "
                         "counter")
            for kind, secs in sorted(
                    obs.compile_seconds_by_kind().items()):
                lines.append(
                    "vllm:engine_compile_seconds_total{kind=\""
                    f"{kind}\"}} {float(secs)}")
            lines.append("# TYPE vllm:engine_executable_cache_size "
                         "gauge")
            for kind, size in sorted(
                    obs.executable_cache_sizes().items()):
                lines.append(
                    "vllm:engine_executable_cache_size{kind=\""
                    f"{kind}\"}} {float(size)}")
            lines.append("# TYPE vllm:engine_hbm_bytes gauge")
            for category, nbytes in sorted(obs.hbm_bytes().items()):
                lines.append("vllm:engine_hbm_bytes{category=\""
                             f"{category}\"}} {float(nbytes)}")
            lines.append(
                "# TYPE vllm:engine_step_device_seconds_total counter")
            for kind, secs in sorted(
                    obs.device_seconds_by_kind().items()):
                lines.append(
                    "vllm:engine_step_device_seconds_total{kind=\""
                    f"{kind}\"}} {float(secs)}")
            lines.append(
                "# TYPE vllm:engine_step_time_median_seconds gauge")
            for kind, med in sorted(obs.step_time_medians().items()):
                lines.append(
                    "vllm:engine_step_time_median_seconds{kind=\""
                    f"{kind}\"}} {float(med)}")
            lines.append("# TYPE vllm:engine_mfu gauge")
            lines.append(f"vllm:engine_mfu {float(obs.mfu())}")
            lines.append("# TYPE vllm:engine_attention_impl gauge")
            for phase, impl in sorted(obs.attention_impls().items()):
                lines.append("vllm:engine_attention_impl{phase=\""
                             f"{phase}\",impl=\"{impl}\"}} 1.0")
        # Topology observability (docs/parallelism.md): the mesh the
        # engine actually runs on, which slice this process owns, and
        # per-slice liveness from the multihost bridge (a dead host
        # names ONE slice here instead of indicting the whole pool).
        lines.append("# TYPE vllm:engine_mesh_shape gauge")
        mesh = getattr(self.engine.runner, "mesh", None)
        par = self.engine.config.parallel
        axis_sizes = (dict(zip(mesh.axis_names, mesh.devices.shape))
                      if mesh is not None else
                      {"dp": 1, "pp": par.pipeline_parallel_size,
                       "sp": par.context_parallel_size,
                       "tp": par.tensor_parallel_size})
        for axis in ("dp", "pp", "sp", "tp"):
            lines.append("vllm:engine_mesh_shape{axis=\""
                         f"{axis}\"}} "
                         f"{float(axis_sizes.get(axis, 1))}")
        lines.append("# TYPE vllm:engine_slice_id gauge")
        lines.append(
            f"vllm:engine_slice_id {float(self._slice_id())}")
        lines.append("# TYPE vllm:engine_slice_live gauge")
        bridge = getattr(self.engine.runner, "bridge", None)
        if bridge is not None:
            live_map = bridge.check_liveness()
        else:
            live_map = {self._slice_id(): True}
        for slice_id, live in sorted(live_map.items()):
            lines.append("vllm:engine_slice_live{slice=\""
                         f"{slice_id}\"}} {float(live)}")
        # vLLM-parity request-latency histograms + token counters.
        lines.extend(self.engine.metrics.render())
        lines.append("")
        return web.Response(text="\n".join(lines),
                            content_type="text/plain")

    async def autotune_status(self, request: web.Request
                              ) -> web.Response:
        """Self-tuning introspection (docs/autotuning.md): mode,
        cadence, and per-controller knob/clamp/frozen/decision
        state."""
        return web.json_response(self.autotuner.status())

    async def autotune_reset(self, request: web.Request
                             ) -> web.Response:
        """Operator reset for guardrail freezes: unlatch one
        controller ({"controller": name}) or all (empty body)."""
        try:
            body = await request.json()
        except Exception:
            body = {}
        name = (body or {}).get("controller")
        cleared = self.autotuner.reset(name)
        return web.json_response({"reset": cleared})

    def build_app(self) -> web.Application:
        app = web.Application(client_max_size=1024 ** 3)
        app.router.add_post("/v1/chat/completions",
                            self._guarded(self.chat_completions))
        app.router.add_post("/v1/completions",
                            self._guarded(self.completions))
        app.router.add_post("/v1/disagg/prefill",
                            self._guarded(self.disagg_prefill))
        app.router.add_post("/v1/disagg/handoff",
                            self._guarded(self.disagg_handoff))
        app.router.add_post("/v1/resume", self._guarded(self.resume))
        app.router.add_post("/drain", self.drain)
        app.router.add_post("/v1/embeddings", self.embeddings)
        app.router.add_post("/v1/score", self.score)
        app.router.add_post("/score", self.score)
        app.router.add_post("/v1/rerank", self.rerank)
        app.router.add_post("/rerank", self.rerank)
        app.router.add_get("/v1/models", self.models)
        app.router.add_get("/health", self.health)
        app.router.add_get("/version", self.version)
        app.router.add_get("/metrics", self.metrics)
        app.router.add_get("/kv/summary", self.kv_summary_handler)
        app.router.add_get("/autotune/status", self.autotune_status)
        app.router.add_post("/autotune/reset", self.autotune_reset)
        app.router.add_post("/debug/profiler/start", self.profiler_start)
        app.router.add_post("/debug/profiler/stop", self.profiler_stop)
        app.router.add_get("/debug/trace/{request_id}", self.debug_trace)
        app.router.add_get("/debug/steps", self.debug_steps)
        app.router.add_get("/debug/compiles", self.debug_compiles)
        app.router.add_get("/debug/memory", self.debug_memory)

        async def on_startup(app):
            self.async_engine.start(asyncio.get_event_loop())

        app.on_startup.append(on_startup)
        return app


# ---- CLI -------------------------------------------------------------------


def _resolve_deferred_kv(args, model_config) -> bool:
    """--deferred-kv-writes auto|on|off -> bool.

    'auto' serves the measured winner where the capability guards
    pass (model_runner rejects ineligible explicit 'on' loudly):
    round-5 on-chip, deferring decode KV writes to one batched flush
    per burst measured +15%% engine throughput (12.76 vs 11.07 req/s,
    benchmarks/results/round5_notes.md)."""
    if args.deferred_kv_writes == "on":
        return True
    if args.deferred_kv_writes == "off":
        return False
    from production_stack_tpu.engine.model_runner import (
        deferred_kv_eligible,
    )
    return deferred_kv_eligible(
        model_config.architecture, args.decode_steps,
        args.attention_impl, args.pipeline_parallel_size,
        args.context_parallel_size, args.speculative_k)


def _resolve_async_scheduling(args) -> bool:
    """--async-scheduling auto|on|off -> bool.

    'auto' enables the overlapped plan/dispatch/complete pipeline
    (docs/async_pipeline.md) for pure single-host single-step decode
    serving: multi-step bursts and speculative decoding already
    amortize the host round trip on device, so 'auto' keeps the
    pipeline off there, and the multihost step bridge broadcasts
    host-resident payloads. An explicit 'on' is legal alongside
    bursts and --speculative-k (docs/unified_step.md
    §dissolved-rules): bursts run as synchronous pipeline breaks and
    verify steps reconcile through the assume-1 stale-drop path. A
    prefill-role engine (docs/disaggregation.md) has no decode steps
    to overlap, so 'auto' resolves off and an explicit 'on' is
    legal but inert."""
    if args.async_scheduling == "on":
        return True
    if args.async_scheduling == "off":
        return False
    if getattr(args, "engine_role", "both") == "prefill":
        return False
    from production_stack_tpu.engine.model_runner import (
        async_scheduling_eligible,
    )
    return async_scheduling_eligible(
        args.decode_steps, args.speculative_k,
        distributed=args.distributed)


def _resolve_unified_step(args) -> bool:
    """--unified-step auto|on|off -> bool.

    'auto' enables the unified ragged step (docs/unified_step.md) —
    prefill chunks admitted into decode steps as one fixed-shape
    mixed batch — wherever it can run: single-host, no pp/sp
    sharding, a monolithic engine role. An explicit 'on' outside
    that envelope fails loudly at runner init
    (model_runner.unified_step_eligible)."""
    if args.unified_step == "on":
        return True
    if args.unified_step == "off":
        return False
    from production_stack_tpu.engine.model_runner import (
        unified_step_eligible,
    )
    return unified_step_eligible(
        args.pipeline_parallel_size, args.context_parallel_size,
        distributed=args.distributed,
        engine_role=getattr(args, "engine_role", "both"))


def build_engine_from_args(args) -> tuple[LLMEngine, str]:
    mesh = None
    if args.model in ("tiny-llama", "tiny-opt"):
        model_config = tiny_model_config(args.model.split("-")[1])
        params = None
        # bench (not byte) tokenizer: random-weight greedy ids land
        # uniformly in the 512 vocab, and ByteTokenizer.decode drops
        # ids >= 256 — streaming clients would lose those deltas.
        # vocab_size threaded from the model so vocab-sized consumers
        # agree with what the engine can emit.
        from production_stack_tpu.engine.tokenizer import BenchTokenizer
        tokenizer = BenchTokenizer(model_config.vocab_size)
        served_name = args.served_model_name or args.model
    elif args.model == "bench-1b":
        # The 1B-class bench geometry (shared with bench.py via
        # config.bench_1b_model_config), random weights + bench
        # tokenizer: lets benchmarks/chip_sweep.sh drive the real HTTP
        # server at bench scale without a checkpoint on disk. The
        # bench tokenizer (not byte): random-weight greedy tokens are
        # almost surely >= 256, which ByteTokenizer.decode drops —
        # streaming clients would see zero non-empty deltas (no TTFT
        # signal, gen_tokens 0).
        model_config = bench_1b_model_config()
        params = None
        from production_stack_tpu.engine.tokenizer import BenchTokenizer
        tokenizer = BenchTokenizer(model_config.vocab_size)
        served_name = args.served_model_name or args.model
    else:
        from production_stack_tpu.engine.weights import (
            load_model_config,
            load_weights,
        )
        model_config = load_model_config(args.model)
        if args.dtype:
            model_config.dtype = args.dtype
        params = (None if args.random_weights
                  else load_weights(args.model, model_config))
        tokenizer = get_tokenizer(args.tokenizer or args.model)
        served_name = args.served_model_name or args.model
    model_config.quantization = args.quantization
    model_config.attention_impl = args.attention_impl

    if (args.tensor_parallel_size > 1
            or args.pipeline_parallel_size > 1
            or args.context_parallel_size > 1
            or args.num_slices > 1):
        from production_stack_tpu.parallel.mesh import build_mesh
        from production_stack_tpu.parallel.topology import (
            parse_placement,
        )
        mesh = build_mesh(
            tensor_parallel_size=args.tensor_parallel_size,
            pipeline_parallel_size=args.pipeline_parallel_size,
            context_parallel_size=args.context_parallel_size,
            num_slices=args.num_slices,
            placement=parse_placement(args.mesh_placement),
        )

    config = EngineConfig(
        model=model_config,
        cache=CacheConfig(
            page_size=args.page_size,
            num_pages=args.num_pages,
            enable_prefix_caching=not args.disable_prefix_caching,
            cache_layout=args.cache_layout,
            kv_cache_dtype=args.kv_cache_dtype,
        ),
        scheduler=SchedulerConfig(
            max_num_seqs=args.max_num_seqs,
            max_model_len=args.max_model_len,
            prefill_chunk_size=args.prefill_chunk_size,
            prefill_batch_size=args.prefill_batch_size,
            decode_steps=args.decode_steps,
            deferred_kv_writes=_resolve_deferred_kv(args, model_config),
            speculative_k=args.speculative_k,
            speculative_min_match=args.speculative_min_match,
            async_scheduling=_resolve_async_scheduling(args),
            unified_step=_resolve_unified_step(args),
            max_queue_len=args.max_queue_len,
        ),
        parallel=ParallelConfig(
            tensor_parallel_size=args.tensor_parallel_size,
            pipeline_parallel_size=args.pipeline_parallel_size,
            context_parallel_size=args.context_parallel_size,
            long_prefill_threshold=args.long_prefill_threshold,
            num_slices=args.num_slices,
            mesh_placement=args.mesh_placement,
        ),
        offload=OffloadConfig(
            enable=args.enable_kv_offload or bool(args.kv_remote_url),
            host_pool_bytes=args.kv_host_pool_bytes,
            remote_url=args.kv_remote_url,
        ),
        lora=LoRAConfig(
            enable=args.enable_lora or bool(args.lora_modules),
            max_loras=args.max_loras,
            max_lora_rank=args.max_lora_rank,
        ),
        qos=QoSConfig(
            default_priority=args.default_priority,
            preempt_to_offload=args.preempt_to_offload == "on",
            shed_threshold=args.shed_threshold,
        ),
        kvecon=KVEconConfig(
            summary_top_k=args.kv_summary_top_k,
            admit_hits=args.kv_admit_hits,
            ttl_s=args.kv_ttl_s,
            watermark_high=args.kv_watermark_high,
            watermark_low=args.kv_watermark_low,
        ),
        autotune=AutotuneConfig(
            mode=args.autotune,
            interval_s=args.autotune_interval_s,
            dead_band=args.autotune_dead_band,
            controllers=args.autotune_controllers,
            freeze_window_s=args.autotune_freeze_window_s,
            burn_threshold=args.autotune_burn_threshold,
            target_itl_ms=args.autotune_target_itl_ms,
            min_spec_k=args.autotune_min_spec_k,
            min_checkpoint_interval_tokens=(
                args.autotune_min_checkpoint_interval_tokens),
            max_checkpoint_interval_tokens=(
                args.autotune_max_checkpoint_interval_tokens),
            min_shed_threshold=args.autotune_min_shed_threshold,
        ),
        seed=args.seed,
        engine_role=args.engine_role,
        handoff_timeout_s=args.handoff_timeout_s,
        device_peak_flops=args.device_peak_flops,
        checkpoint_interval_tokens=args.checkpoint_interval_tokens,
        step_watchdog_s=args.step_watchdog_s,
    )
    engine = LLMEngine(config, mesh=mesh, params=params,
                       tokenizer=tokenizer)
    for module in args.lora_modules or []:
        name, _, path = module.partition("=")
        if not path:
            raise ValueError(
                f"--lora-modules entries must be name=path, got {module!r}"
            )
        engine.register_lora(path, name=name)
    if args.request_span_log or args.trace_ring_size > 0:
        # Server default: flight recorder on (ring > 0), span log off.
        # Library/tests constructing LLMEngine directly keep
        # engine.tracer None — zero tracing cost there.
        from production_stack_tpu.engine.tracing import EngineTracer
        engine.tracer = EngineTracer(
            span_log_path=args.request_span_log,
            ring_size=max(1, args.trace_ring_size),
            role=args.engine_role,
        )
    return engine, served_name


def parse_args(argv=None):
    parser = argparse.ArgumentParser(prog="tpu-engine")
    parser.add_argument("--model", default="tiny-llama",
                        help="HF model dir, or tiny-llama/tiny-opt")
    parser.add_argument("--served-model-name", default=None)
    parser.add_argument("--tokenizer", default=None)
    parser.add_argument("--random-weights", action="store_true")
    parser.add_argument("--dtype", default=None,
                        choices=[None, "bfloat16", "float32", "float16"])
    parser.add_argument("--attention-impl", default="auto",
                        choices=["auto", "xla", "pallas",
                                 "pallas-interpret"],
                        help="auto = empirical dispatch by the "
                             "measured-winner table (model_runner)")
    parser.add_argument("--quantization", default="none",
                        choices=["none", "int8"],
                        help="Weight-only quantization (halves weight "
                             "HBM traffic on the decode path)")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--page-size", type=int, default=16)
    parser.add_argument("--num-pages", type=int, default=512)
    parser.add_argument("--kv-cache-dtype", default="auto",
                        choices=["auto", "bf16", "int8"],
                        help="KV page storage dtype. 'auto'/'bf16' "
                             "store pages in the model dtype; 'int8' "
                             "quantizes pages with per-slot per-head "
                             "scales and expands the page budget "
                             "~2x at the same HBM bytes "
                             "(docs/kv_quantization.md)")
    parser.add_argument("--cache-layout", default="auto",
                        choices=["auto", "stacked", "per_layer"],
                        help="KV cache HBM layout: auto (measured "
                             "winner: per_layer unless pp/sp), one "
                             "stacked [L,...] array, or a tuple of "
                             "per-layer buffers (engine/config.py "
                             "CacheConfig)")
    parser.add_argument("--max-num-seqs", type=int, default=8)
    parser.add_argument("--max-model-len", type=int, default=2048)
    parser.add_argument("--prefill-chunk-size", type=int, default=512)
    parser.add_argument("--prefill-batch-size", type=int, default=4)
    parser.add_argument("--decode-steps", type=int, default=1,
                        help="Decode iterations fused per compiled "
                             "program (K tokens per host round-trip)")
    parser.add_argument("--speculative-k", type=int, default=0,
                        help="Draft-free speculative decoding: propose "
                             "up to K tokens per row via prompt lookup "
                             "and verify K+1 positions in one pass "
                             "(docs/speculative.md). 0 = off. Draft-"
                             "less steps fall back to the --decode-"
                             "steps burst; incompatible with "
                             "--deferred-kv-writes on")
    parser.add_argument("--speculative-min-match", type=int, default=2,
                        help="Minimum n-gram match length before the "
                             "prompt-lookup proposer drafts")
    parser.add_argument("--async-scheduling", default="auto",
                        choices=["auto", "on", "off"],
                        help="Overlapped async execution pipeline: "
                             "plan + dispatch decode step N+1 before "
                             "step N's tokens are read back, hiding "
                             "host work behind the device step "
                             "(docs/async_pipeline.md). 'auto' "
                             "enables it for single-host single-step "
                             "decode (off under --decode-steps > 1, "
                             "--speculative-k > 0, --distributed)")
    parser.add_argument("--unified-step", default="auto",
                        choices=["auto", "on", "off"],
                        help="Unified ragged step: admit prefill "
                             "chunks into decode steps as one fixed-"
                             "shape mixed batch instead of "
                             "alternating whole steps "
                             "(docs/unified_step.md). 'auto' enables "
                             "it for single-host monolithic serving "
                             "(off under pp/sp sharding, "
                             "--distributed, a disagg --engine-role)")
    parser.add_argument("--deferred-kv-writes", default="auto",
                        choices=["auto", "on", "off"],
                        help="Defer decode KV writes to one batched "
                             "flush per burst (round-5 measured +15%% "
                             "decode throughput). 'auto' enables it "
                             "when eligible (llama-family, "
                             "decode-steps > 1, xla decode, no pp/sp)")
    parser.add_argument("--tensor-parallel-size", type=int, default=1)
    parser.add_argument("--pipeline-parallel-size", type=int, default=1,
                        help="Layer stages over the pp mesh axis "
                             "(serving-path pipeline parallelism)")
    parser.add_argument("--context-parallel-size", type=int, default=1,
                        help="Sequence shards over the sp mesh axis: "
                             "long prompts prefill in one ring-"
                             "attention dispatch "
                             "(parallel/context_serving.py)")
    parser.add_argument("--long-prefill-threshold", type=int,
                        default=None,
                        help="Prompt length (tokens) that takes the "
                             "context-parallel prefill path (default "
                             "2 x prefill-chunk-size)")
    parser.add_argument("--num-slices", type=int, default=0,
                        help="Force the device topology into N equal "
                             "contiguous slices (CPU harness / "
                             "override); 0 auto-discovers ICI or "
                             "process grouping (parallel/topology.py)")
    parser.add_argument("--mesh-placement", default="auto",
                        help="Per-axis mesh placement as 'axis=ici' / "
                             "'axis=any' pairs (comma separated); "
                             "'auto' keeps tp/sp inside one ICI "
                             "domain and lets dp/pp cross slices")
    parser.add_argument("--disable-prefix-caching", action="store_true")
    parser.add_argument("--enable-lora", action="store_true",
                        help="Enable multi-LoRA adapter serving")
    parser.add_argument("--lora-modules", nargs="*", default=None,
                        metavar="NAME=PATH",
                        help="PEFT adapter dirs to serve by name")
    parser.add_argument("--max-loras", type=int, default=8)
    parser.add_argument("--max-lora-rank", type=int, default=16)
    parser.add_argument("--pooling", default="last",
                        choices=["last", "mean"],
                        help="/v1/embeddings pooling mode")
    parser.add_argument("--chat-template", default=None,
                        help="Jinja chat template source or file path, "
                             "overriding the model's own template")
    parser.add_argument("--profile-dir", "--profiler-dir",
                        dest="profile_dir", default=None,
                        help="Default output dir for "
                             "/debug/profiler/start traces "
                             "(--profiler-dir is an alias)")
    parser.add_argument("--device-peak-flops", type=float, default=0.0,
                        help="Per-chip peak FLOP/s for the "
                             "observatory's vllm:engine_mfu gauge; 0 "
                             "resolves from the device-kind table "
                             "(unknown devices report MFU 0)")
    parser.add_argument("--request-span-log", default=None,
                        help="Emit one JSON engine-span line per "
                             "finished request to this path ('-' = "
                             "the engine log). Same span family as "
                             "the router's --request-span-log; stitch "
                             "with python -m "
                             "production_stack_tpu.traceview "
                             "(docs/observability.md)")
    parser.add_argument("--trace-ring-size", type=int, default=256,
                        help="Flight-recorder depth: recent request "
                             "timelines kept for /debug/trace/{id} "
                             "and step records for /debug/steps. "
                             "0 disables the recorder (and, with no "
                             "--request-span-log, all tracing)")
    parser.add_argument("--compilation-cache-dir", default=None,
                        help="Persistent XLA compilation cache (point "
                             "at the PVC so pod restarts skip "
                             "recompilation)")
    # Multi-host slice serving (jax.distributed; parallel/distributed.py).
    # On GKE TPU slices the three values auto-detect — pass none of them.
    parser.add_argument("--distributed", action="store_true",
                        help="Join a jax.distributed multi-host slice")
    parser.add_argument("--coordinator-address", default=None)
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)
    parser.add_argument("--enable-kv-offload", action="store_true",
                        help="HBM->host-RAM KV offload tier")
    parser.add_argument("--kv-host-pool-bytes", type=int,
                        default=2 * 1024 ** 3)
    parser.add_argument("--kv-remote-url", default=None,
                        help="Remote shared KV cache server URL")
    parser.add_argument("--max-queue-len", type=int, default=1024,
                        help="Waiting-queue depth before submissions "
                             "are rejected (scheduler backpressure)")
    parser.add_argument("--seed", type=int, default=0,
                        help="Base RNG seed for sampled requests "
                             "without a per-request seed")
    parser.add_argument("--engine-role", default="both",
                        choices=["prefill", "decode", "both"],
                        help="Disaggregated serving role "
                             "(docs/disaggregation.md): 'prefill' "
                             "computes prompt KV and hands off via "
                             "the offload wire, 'decode' resumes "
                             "handoffs, 'both' (default) serves "
                             "monolithically. Advertised via /health "
                             "for role-aware routing")
    parser.add_argument("--default-priority", default="batch",
                        choices=list(PRIORITY_NAMES),
                        help="QoS class assumed for requests without "
                             "an x-priority header (docs/qos.md). "
                             "Priority orders waiting-queue admission "
                             "and picks preemption victims "
                             "(lowest class, newest arrival first)")
    parser.add_argument("--preempt-to-offload", default="on",
                        choices=["on", "off"],
                        help="Under KV page pressure, ship a preempted "
                             "victim's committed pages to the "
                             "configured offload tier and restore "
                             "them on re-admission instead of "
                             "recomputing (docs/qos.md). Inert "
                             "without --enable-kv-offload or "
                             "--kv-remote-url")
    parser.add_argument("--shed-threshold", type=float, default=0.95,
                        help="Fraction of --max-queue-len at which "
                             "non-interactive requests are shed with "
                             "429 + Retry-After instead of queued "
                             "(docs/qos.md); interactive requests are "
                             "never shed by this gate")
    parser.add_argument("--handoff-timeout-s", type=float, default=30.0,
                        help="How long a decode-role engine holds a "
                             "handoff in AWAITING_KV waiting for an "
                             "unreachable offload tier before "
                             "degrading to full recompute")
    parser.add_argument("--drain-exit-timeout-s", type=float,
                        default=0.0,
                        help="After POST /drain {\"exit\": true}, the "
                             "longest the server waits for in-flight "
                             "requests before exiting anyway (0 = "
                             "wait forever; the fleet manager applies "
                             "its own drain deadline)")
    parser.add_argument("--build-id", type=str, default="",
                        help="Opaque build/revision label reported in "
                             "/health and /version; the fleet rollout "
                             "controller uses it to verify which "
                             "revision a replica runs (docs/fleet.md)")
    parser.add_argument("--checkpoint-interval-tokens", type=int,
                        default=0,
                        help="Every N generated tokens, ship a "
                             "streaming sequence's committed KV pages "
                             "to the offload tier and attach a resume "
                             "descriptor to the SSE stream so the "
                             "router can resume it on another engine "
                             "after a crash (0 disables; "
                             "docs/crash_recovery.md)")
    parser.add_argument("--step-watchdog-s", type=float, default=0.0,
                        help="Seconds a single engine step may run "
                             "before /health flips to 503 so the "
                             "router's prober rotates the hung "
                             "replica out (0 disables)")
    # Cluster KV economy (docs/kv_economy.md): the GET /kv/summary
    # hot-chain tracker and the offload tier's watermark hysteresis.
    parser.add_argument("--kv-summary-top-k", type=int, default=64,
                        help="Hot prefix chains advertised at "
                             "GET /kv/summary for KV-state-aware "
                             "routing (docs/kv_economy.md)")
    parser.add_argument("--kv-admit-hits", type=int, default=2,
                        help="Decayed hit count a prefix chain needs "
                             "before the summary advertises it")
    parser.add_argument("--kv-ttl-s", type=float, default=900.0,
                        help="Seconds an idle prefix chain stays in "
                             "the summary tracker (0 disables TTL)")
    parser.add_argument("--kv-watermark-high", type=float, default=1.0,
                        help="Host KV pool fill fraction that triggers "
                             "LRU eviction (1.0 = legacy exact-"
                             "capacity behavior)")
    parser.add_argument("--kv-watermark-low", type=float, default=1.0,
                        help="Fill fraction the host KV pool drains "
                             "down to once the high watermark trips")
    # Self-tuning controllers (docs/autotuning.md).
    parser.add_argument("--autotune", default="off",
                        choices=["off", "shadow", "on"],
                        help="Self-tuning controllers: off, shadow "
                             "(compute + span-log decisions without "
                             "applying), or on (close the loop)")
    parser.add_argument("--autotune-interval-s", type=float,
                        default=2.0,
                        help="Seconds between controller ticks")
    parser.add_argument("--autotune-dead-band", type=float,
                        default=0.05,
                        help="Relative dead-band: drop proposals "
                             "within this fraction of the current "
                             "knob value")
    parser.add_argument("--autotune-controllers", default="all",
                        help="Comma-separated controller allowlist "
                             "(spec_k,prefill_budget,kvecon,"
                             "checkpoint_interval,qos_shed) or 'all'")
    parser.add_argument("--autotune-freeze-window-s", type=float,
                        default=30.0,
                        help="Guardrail blame window: freeze "
                             "controllers that applied a decision "
                             "this recently when perf drift flips "
                             "or 5m burn rises")
    parser.add_argument("--autotune-burn-threshold", type=float,
                        default=1.0,
                        help="5m SLO burn rate at/above which a rise "
                             "trips the guardrail")
    parser.add_argument("--autotune-target-itl-ms", type=float,
                        default=50.0,
                        help="Decode ITL p99 target the prefill-"
                             "budget controller steers toward")
    parser.add_argument("--autotune-min-spec-k", type=int, default=1,
                        help="Floor for the per-sequence speculative "
                             "draft cap (ceiling is --speculative-k)")
    parser.add_argument("--autotune-min-checkpoint-interval-tokens",
                        type=int, default=64,
                        help="Floor for the tuned checkpoint "
                             "interval")
    parser.add_argument("--autotune-max-checkpoint-interval-tokens",
                        type=int, default=4096,
                        help="Ceiling for the tuned checkpoint "
                             "interval")
    parser.add_argument("--autotune-min-shed-threshold", type=float,
                        default=0.5,
                        help="Floor for the tuned QoS shed gate "
                             "(ceiling is --shed-threshold)")
    return parser.parse_args(argv)


def _load_chat_template(args) -> Optional[str]:
    """--chat-template accepts inline Jinja source or a file path."""
    import os
    if not args.chat_template:
        return None
    if os.path.exists(args.chat_template):
        with open(args.chat_template) as f:
            source = f.read()
    else:
        source = args.chat_template
    # Fail fast on a broken template: a render failure at request time
    # silently falls back to the model's template (tokenizer.py), which
    # an operator who set the flag should learn at startup instead.
    import jinja2
    jinja2.Template(source).render(
        messages=[{"role": "user", "content": "probe"}],
        add_generation_prompt=True,
    )
    return source


def main(argv=None) -> None:
    import os
    # Honor an explicit JAX_PLATFORMS request. The TPU-tunnel image's
    # sitecustomize overrides jax_platforms via jax.config (config
    # beats env), which would make `JAX_PLATFORMS=cpu tpu-engine ...`
    # silently dial the tunnel anyway — and hang if it is down.
    requested = os.environ.get("JAX_PLATFORMS", "").strip()
    if requested:
        try:
            import jax
            jax.config.update("jax_platforms", requested)
        except Exception:
            pass
    args = parse_args(argv)
    if args.compilation_cache_dir:
        # Persistent executable cache: a restarted pod (weight PVC +
        # this cache) resumes serving without the cold-compile wait —
        # the serving-side resume story (SURVEY.md §5).
        import jax
        jax.config.update("jax_compilation_cache_dir",
                          args.compilation_cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)
    if args.distributed:
        from production_stack_tpu.parallel.distributed import (
            MultihostStepBridge,
            init_distributed,
            is_coordinator,
        )
        if args.enable_kv_offload or args.kv_remote_url:
            raise ValueError(
                "KV offload tiers are host-0-local state and are not "
                "yet supported in multi-host mode"
            )
        if args.context_parallel_size > 1:
            # Fail at startup, not on the first long prompt: sp
            # prefill payloads are not mirrored over the step bridge
            # yet (model_runner.run_sp_prefill), and a mid-serving
            # NotImplementedError would wedge the worker hosts.
            raise ValueError(
                "--context-parallel-size > 1 is not yet supported "
                "with --distributed (single-host sp only)"
            )
        init_distributed(args.coordinator_address, args.num_processes,
                         args.process_id)
        engine, served_name = build_engine_from_args(args)
        # Size the liveness ledger from the discovered topology so a
        # dead host's missing acks name one slice on /metrics.
        from production_stack_tpu.parallel.topology import (
            discover_topology,
        )
        topo = discover_topology(num_slices=args.num_slices)
        bridge = MultihostStepBridge(engine.runner,
                                     num_slices=topo.num_slices)
        # Build the embedder on EVERY host now: embed programs run
        # collectives over the global mesh, so workers must be able to
        # mirror KIND_EMBED payloads — a host-0-only lazy build would
        # deadlock the slice on the first /v1/embeddings request.
        try:
            from production_stack_tpu.engine.embeddings import Embedder
            embedder = Embedder(
                engine.config.model, engine.runner.params,
                max_len=engine.config.scheduler.max_model_len,
                pooling=args.pooling,
            )
            engine.runner.embedder = embedder
        except NotImplementedError as e:
            logger.info("embeddings/score/rerank disabled on this "
                        "slice: %s", e)
            embedder = None
        if not is_coordinator():
            # Workers never serve HTTP; they mirror host 0's steps.
            bridge.worker_loop()
            return
        engine.runner.bridge = bridge
        server = EngineServer(engine, served_name, pooling=args.pooling,
                          profile_dir=args.profile_dir,
                          chat_template=_load_chat_template(args),
                          drain_exit_timeout_s=args.drain_exit_timeout_s,
                          build_id=args.build_id)
        if embedder is not None:
            embedder.bridge = bridge
            server._embedder = embedder
        logger.info("tpu-engine %s (multihost coordinator) serving %s "
                    "on %s:%d", __version__, served_name, args.host,
                    args.port)
        try:
            web.run_app(server.build_app(), host=args.host,
                        port=args.port, print=None)
        finally:
            bridge.shutdown()
        return
    engine, served_name = build_engine_from_args(args)
    server = EngineServer(engine, served_name, pooling=args.pooling,
                          profile_dir=args.profile_dir,
                          chat_template=_load_chat_template(args),
                          drain_exit_timeout_s=args.drain_exit_timeout_s,
                          build_id=args.build_id)
    logger.info("tpu-engine %s serving %s on %s:%d",
                __version__, served_name, args.host, args.port)
    web.run_app(server.build_app(), host=args.host, port=args.port,
                print=None)


if __name__ == "__main__":
    main()
