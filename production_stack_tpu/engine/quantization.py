"""Weight-only int8 quantization for serving.

Decode on TPU is HBM-bandwidth-bound on weight streaming; storing the
projection matrices as int8 with per-output-channel scales halves that
traffic (the weight-only-quantization recipe vLLM exposes via
--quantization; here it is a load-time transform, no calibration data
needed for symmetric weight-only).

Representation: a quantized weight is the pytree pair
``(w_int8 [L, in, out], scale [L, out] f32)``; the matmul helper
(engine/lora.py lora_matmul) computes ``(x @ w_int8) * scale`` — XLA
fuses the int8->bf16 convert and the scale into the dot's epilogue, so
only int8 bytes ever cross HBM. Activations stay bf16; the MXU result
is rescaled per channel.

Serving-path only: the dense encode/training forwards use the
unquantized layout (the Embedder refuses quantized params).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from production_stack_tpu.engine.config import ModelConfig

QuantizedWeight = Tuple[jnp.ndarray, jnp.ndarray]

# Projection params quantized per architecture (layer-stacked rank-3
# [L, in, out]). Norms, embeddings and biases stay in full precision.
_TARGETS = {
    "llama": ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"),
    "mistral": ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"),
    "qwen2": ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"),
    "opt": ("wq", "wk", "wv", "wo", "fc1", "fc2"),
    "gpt2": ("wq", "wk", "wv", "wo", "fc1", "fc2"),
}


def quantize_weight(w: jnp.ndarray) -> QuantizedWeight:
    """Symmetric per-output-channel int8 over the contraction dim."""
    w32 = w.astype(jnp.float32)
    scale = jnp.max(jnp.abs(w32), axis=-2, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return q, scale.squeeze(-2)  # [L, in, out] -> scale [L, out]


def dequant_matmul(x: jnp.ndarray, qw: QuantizedWeight) -> jnp.ndarray:
    q, scale = qw
    out = x @ q.astype(x.dtype)
    return out * scale.astype(x.dtype)


def is_quantized(w) -> bool:
    return isinstance(w, tuple) and len(w) == 2


def quantize_params(params: Dict, config: ModelConfig) -> Dict:
    targets = _TARGETS.get(config.architecture)
    if targets is None:
        raise NotImplementedError(
            f"--quantization int8 is not supported for "
            f"architecture {config.architecture!r}"
        )
    out = dict(params)
    for name in targets:
        if name in out:
            out[name] = quantize_weight(out[name])
    return out


def has_quantized_leaves(params: Dict) -> bool:
    return any(is_quantized(v) for v in params.values())
