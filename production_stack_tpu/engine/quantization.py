"""Weight-only int8 quantization for serving.

Decode on TPU is HBM-bandwidth-bound on weight streaming; storing the
projection matrices as int8 with per-output-channel scales halves that
traffic (the weight-only-quantization recipe vLLM exposes via
--quantization; here it is a load-time transform, no calibration data
needed for symmetric weight-only).

Representation: a quantized weight is the pytree pair
``(w_int8 [L, in, out], scale [L, out] f32)``; the matmul helper
(engine/lora.py lora_matmul) computes ``(x @ w_int8) * scale`` — XLA
fuses the int8->bf16 convert and the scale into the dot's epilogue, so
only int8 bytes ever cross HBM. Activations stay bf16; the MXU result
is rescaled per channel.

Serving-path only: the dense encode/training forwards use the
unquantized layout (the Embedder refuses quantized params).

Weights are one of the two int8 serving knobs; the other is the KV
cache. ``--kv-cache-dtype int8`` (CacheConfig.kv_cache_dtype) stores
KV *pages* as int8 with per-slot per-head scales — quantized on the
page write path (ops/attention.write_to_pages), dequantized in-kernel
on the attention read path — and expands the page budget ~2x at the
same HBM bytes. The two compose freely: this module covers weight
streaming bandwidth, the KV knob covers cache capacity + decode read
bandwidth (ops/quant_kv.py, docs/kv_quantization.md).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from production_stack_tpu.engine.config import ModelConfig

QuantizedWeight = Tuple[jnp.ndarray, jnp.ndarray]

# Projection params quantized per architecture (layer-stacked rank-3
# [L, in, out]). Norms, embeddings and biases stay in full precision.
_TARGETS = {
    "llama": ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"),
    "mistral": ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"),
    "qwen2": ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"),
    "opt": ("wq", "wk", "wv", "wo", "fc1", "fc2"),
    "gpt2": ("wq", "wk", "wv", "wo", "fc1", "fc2"),
}


def quantize_weight(w: jnp.ndarray) -> QuantizedWeight:
    """Symmetric per-output-channel int8 over the contraction dim."""
    w32 = w.astype(jnp.float32)
    scale = jnp.max(jnp.abs(w32), axis=-2, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return q, scale.squeeze(-2)  # [L, in, out] -> scale [L, out]


def dequant_matmul(x: jnp.ndarray, qw: QuantizedWeight) -> jnp.ndarray:
    q, scale = qw
    out = x @ q.astype(x.dtype)
    return out * scale.astype(x.dtype)


def is_quantized(w) -> bool:
    return isinstance(w, tuple) and len(w) == 2


def quantize_params(params: Dict, config: ModelConfig) -> Dict:
    targets = _TARGETS.get(config.architecture)
    if targets is None:
        raise NotImplementedError(
            f"--quantization int8 is not supported for "
            f"architecture {config.architecture!r}"
        )
    out = dict(params)
    for name in targets:
        if name in out:
            out[name] = quantize_weight(out[name])
    return out


def has_quantized_leaves(params: Dict) -> bool:
    return any(is_quantized(v) for v in params.values())


def init_random_quantized(init_fn, config: ModelConfig,
                          seed: int) -> Dict:
    """Random-init an int8 model WITHOUT materializing it in full
    precision.

    ``init_fn`` followed by :func:`quantize_params` peaks at the full
    bf16 model plus f32 quantization copies on device — a 16 GB HBM
    chip cannot hold that for an 8B model even though the final int8
    footprint (~8 GB) fits comfortably (observed: RESOURCE_EXHAUSTED
    on the round-5 8B bench, results/round5_notes.md). Random weights
    carry no information worth quantizing, so the projection targets
    are sampled directly as int8 (uniform) with a flat per-channel
    scale matching the init distribution's magnitude; only the
    non-target leaves (embeddings, norms, biases) are materialized in
    their full dtype. Peak device memory = the final serving
    footprint. Leaf names/shapes come from ``jax.eval_shape`` so
    every model family's init stays the single source of truth.
    """
    import numpy as np

    targets = _TARGETS.get(config.architecture)
    if targets is None:
        raise NotImplementedError(
            f"--quantization int8 is not supported for "
            f"architecture {config.architecture!r}"
        )
    import dataclasses
    import functools

    shapes = jax.eval_shape(functools.partial(init_fn, config),
                            jax.random.PRNGKey(seed & 0x7FFFFFFF))
    # Leaf *semantics* (ones for norm gains, zeros for biases, random
    # for dense) come from materializing the SAME init at a shrunken
    # geometry — the family's init stays the single source of truth;
    # no name heuristics to silently misclassify a new architecture's
    # leaves.
    probe_cfg = dataclasses.replace(
        config, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, vocab_size=256,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        max_position_embeddings=64)
    probe = init_fn(probe_cfg, jax.random.PRNGKey(0))
    kinds = {}
    for name, leaf in probe.items():
        a = np.asarray(jax.device_get(leaf), np.float32)
        kinds[name] = ("ones" if np.all(a == 1.0)
                       else "zeros" if np.all(a == 0.0)
                       else "dense")
    if set(kinds) != set(shapes):
        raise AssertionError(
            "init leaf set changed with geometry: "
            f"{sorted(set(kinds) ^ set(shapes))}")
    # np.random.Generator (PCG64): ~4x faster than RandomState at the
    # 8B leaf sizes (the init runs on the bench host and eats
    # chip-window minutes).
    rng = np.random.Generator(np.random.PCG64(seed & 0x7FFFFFFF))
    out: Dict = {}
    for name, sds in shapes.items():
        shape = sds.shape
        if name in targets:
            q = rng.integers(-127, 128, size=shape, dtype=np.int8)
            scale = np.full(shape[:-2] + (shape[-1],), 0.02 / 127.0,
                            np.float32)
            out[name] = (jnp.asarray(q), jnp.asarray(scale))
        elif kinds[name] == "ones":
            out[name] = jnp.ones(shape, sds.dtype)
        elif kinds[name] == "zeros":
            out[name] = jnp.zeros(shape, sds.dtype)
        else:
            host = 0.02 * rng.standard_normal(shape,
                                              dtype=np.float32)
            # Cast on host (ml_dtypes handles bf16) so only the
            # final-dtype bytes land on device — an on-device astype
            # would stage a transient f32 copy of each dense leaf.
            out[name] = jnp.asarray(host.astype(sds.dtype))
    return out
