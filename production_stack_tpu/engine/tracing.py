"""Engine-side request tracing: per-request event timelines, a step
flight recorder, and JSON span lines in the same format family as
``router/tracing.py`` (docs/observability.md).

The router's span stops at the proxy boundary; this module picks the
request up inside the engine, keyed by the router's ``x-request-id``
header, and records the lifecycle events aggregate histograms
structurally cannot show for one request: enqueue, ``AWAITING_KV``
park/restore, each prefill chunk, first token, preemption, offload
restore, handoff ship, finish reason. Two sinks:

- an optional JSON-line span log (``--request-span-log``; ``-`` logs
  via the process logger) emitting one ``{"span": "engine_request"}``
  line per finished request, mergeable with the router's
  ``{"span": "request"}`` lines by ``python -m
  production_stack_tpu.traceview``;
- an always-on (when a tracer is installed) flight recorder: bounded
  rings of recent request timelines and per-step records, served at
  ``/debug/trace/{request_id}`` and ``/debug/steps``.

Concurrency: the engine's device loop, the asyncio handlers, and the
drain path all touch the tracer. Every mutation is a GIL-atomic dict
or ``deque(maxlen=...)`` operation — no lock is taken on the step or
token path. The module is stdlib-only (no JAX, no aiohttp) so the
fake engine reuses it verbatim.

Disabled cost: the engine holds ``tracer = None`` unless a tracer is
explicitly installed; every emission site is behind an ``is None``
check, so the disabled hot path allocates no span objects at all.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

# The closed vocabulary of engine span event names. The staticcheck
# ``span-contract`` rule holds this tuple, every string literal passed
# to ``EngineTracer.event`` / ``EngineSpan.event`` across the package,
# and the event table in docs/observability.md in three-way agreement.
SPAN_EVENTS = (
    "enqueue",
    "awaiting_kv_park",
    "awaiting_kv_restore",
    "offload_restore",
    "prefill_chunk",
    "first_token",
    "preempt",
    "preempt_offload",
    "qos_shed",
    "handoff_ship",
    "profiler_start",
    "profiler_stop",
    "checkpoint_ship",
    "resume_restore",
    "migrate_ship",
    "watchdog_trip",
    "crash_respawn",
    "autotune_decision",
    "finish",
)


def _ms(a: Optional[float], b: Optional[float]) -> Optional[float]:
    if a is None or b is None:
        return None
    return round((b - a) * 1e3, 2)


class EngineSpan:
    """One request's event timeline inside a single engine process."""

    __slots__ = ("seq_id", "request_id", "role", "start_ts", "events",
                 "summary")

    def __init__(self, seq_id: str, request_id: Optional[str],
                 role: str = "both"):
        self.seq_id = seq_id
        self.request_id = request_id
        self.role = role
        self.start_ts = time.time()
        self.events: List[Dict[str, Any]] = []
        self.summary: Dict[str, Any] = {}

    def event(self, name: str, **fields: Any) -> None:
        record: Dict[str, Any] = {"event": name,
                                  "ts": round(time.time(), 6)}
        if fields:
            record.update(fields)
        self.events.append(record)

    def to_dict(self) -> Dict[str, Any]:
        data: Dict[str, Any] = {
            "span": "engine_request",
            "request_id": self.request_id,
            "seq_id": self.seq_id,
            "role": self.role,
            "arrival_ts": round(self.start_ts, 6),
        }
        data.update(self.summary)
        data["events"] = self.events
        return data

    def to_json(self) -> str:
        return json.dumps(self.to_dict())


class _SpanSink:
    """Line-buffered JSON-line sink, same contract as the router's
    SpanLogger: path ``-`` routes through the process logger."""

    def __init__(self, path: str):
        self._lock = threading.Lock()
        self._fh = None
        if path != "-":
            self._fh = open(path, "a", buffering=1)

    def emit(self, line: str) -> None:
        if self._fh is None:
            logger.info("engine-span %s", line)
            return
        with self._lock:
            self._fh.write(line + "\n")


class EngineTracer:
    """Per-request timelines + step flight recorder for one engine.

    Installed on ``LLMEngine.tracer`` (and mirrored onto
    ``Scheduler.tracer``); every caller guards with ``is None`` so an
    engine without a tracer pays nothing.
    """

    def __init__(self, span_log_path: Optional[str] = None,
                 ring_size: int = 256, step_ring_size: int = 512,
                 role: str = "both"):
        self.role = role
        self._live: Dict[str, EngineSpan] = {}
        self._ring: deque = deque(maxlen=max(1, int(ring_size)))
        self._steps: deque = deque(maxlen=max(1, int(step_ring_size)))
        self._step_ids = itertools.count()
        self._sink = (_SpanSink(span_log_path)
                      if span_log_path else None)

    # -- request timeline ---------------------------------------------------

    def start(self, seq_id: str, request_id: Optional[str] = None,
              **fields: Any) -> None:
        span = EngineSpan(seq_id, request_id, role=self.role)
        span.event("enqueue", **fields)
        self._live[seq_id] = span

    def event(self, seq_id: str, name: str, **fields: Any) -> None:
        span = self._live.get(seq_id)
        if span is not None:
            span.event(name, **fields)

    def finish(self, seq_id: str, reason: Optional[str] = None, *,
               arrival_ts: Optional[float] = None,
               first_scheduled_ts: Optional[float] = None,
               first_token_ts: Optional[float] = None,
               finish_ts: Optional[float] = None,
               prompt_tokens: Optional[int] = None,
               output_tokens: Optional[int] = None) -> None:
        """Finalizes a live span: appends the terminal event, derives
        the phase durations, emits the JSON line, and moves the span
        into the flight-recorder ring. Idempotent per seq_id (abort
        and the finished-output drain can race to it)."""
        span = self._live.pop(seq_id, None)
        if span is None:
            return
        span.event("finish", reason=reason)
        arrival = arrival_ts if arrival_ts is not None else span.start_ts
        end = finish_ts if finish_ts is not None else time.time()
        span.summary = {
            "finish_reason": reason,
            "prompt_tokens": prompt_tokens,
            "output_tokens": output_tokens,
            "queue_ms": _ms(arrival, first_scheduled_ts),
            "ttft_ms": _ms(arrival, first_token_ts),
            "decode_ms": _ms(first_token_ts, end),
            "latency_ms": _ms(arrival, end),
        }
        self._ring.append(span)
        if self._sink is not None:
            self._sink.emit(span.to_json())

    # -- step flight recorder -----------------------------------------------

    def on_step(self, **fields: Any) -> None:
        record: Dict[str, Any] = {"step": next(self._step_ids),
                                  "ts": round(time.time(), 6)}
        record.update(fields)
        self._steps.append(record)

    def recent_steps(self, limit: int = 100) -> List[Dict[str, Any]]:
        steps = list(self._steps)
        if limit > 0:
            steps = steps[-limit:]
        return steps

    # -- lookup -------------------------------------------------------------

    def lookup(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """All recorded timelines for one ``x-request-id`` (or engine
        seq id) — live spans first, then the ring, oldest first."""
        spans = [span for span in
                 list(self._live.values()) + list(self._ring)
                 if trace_id in (span.seq_id, span.request_id)]
        if not spans:
            return None
        return {"request_id": trace_id,
                "spans": [s.to_dict() for s in spans]}
