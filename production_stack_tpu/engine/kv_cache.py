"""Paged KV cache management (host-side bookkeeping).

The device arrays live in the model runner; this module owns page
accounting: a free-list allocator plus a refcounted hash-based prefix
cache (the TPU analogue of vLLM's prefix caching +
``--enable-prefix-caching``, which the reference chart passes through at
helm/templates/deployment-vllm-multi.yaml:76-79). Page 0 is reserved as
the trash page that padded writes land on (ops/attention.write_to_pages).

Capacity metrics feed the engine's ``/metrics``:
``vllm:gpu_cache_usage_perc`` and ``vllm:gpu_prefix_cache_hit_rate``
(scraped by the router, reference engine_stats.py:46-55).

Page accounting is storage-dtype agnostic: with ``--kv-cache-dtype
int8`` the EngineConfig expands ``num_pages`` ~2x at the same HBM byte
budget (engine/config.py) before this manager is built, and content
hashes/refcounts are over token ids, so quantized and full-precision
pods share identical prefix-cache semantics.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from production_stack_tpu.engine.config import CacheConfig
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

PageHash = Tuple[int, Tuple[int, ...]]


@dataclass
class PageInfo:
    page_id: int
    ref_count: int = 0
    page_hash: Optional[PageHash] = None


class OutOfPagesError(RuntimeError):
    pass


class PagedCacheManager:
    """Allocates cache pages to sequences; shares full pages by content.

    Prefix sharing: a *full* page is identified by
    ``hash(parent_hash, tokens_in_page)``. When a new sequence's prompt
    starts with an already-cached chain of full pages, those pages are
    reused (ref_count++) and their tokens skip prefill entirely.
    Zero-ref hashed pages stay cached (LRU) until capacity pressure
    evicts them.
    """

    def __init__(self, config: CacheConfig):
        self.config = config
        self.page_size = config.page_size
        # Page 0 is the trash page; never allocated.
        self._free: List[int] = list(range(config.num_pages - 1, 0, -1))
        self._pages: Dict[int, PageInfo] = {}
        self._hash_to_page: Dict[PageHash, int] = {}
        # Zero-ref pages still holding reusable content, LRU order.
        self._evictable: "OrderedDict[int, None]" = OrderedDict()
        # Fired with (page_id, page_hash) just before a hashed page's
        # HBM slot is reused — the offload tier's capture point.
        self.evict_listener = None
        # Stats
        self.prefix_hit_tokens = 0
        self.prefix_query_tokens = 0

    # ---- capacity ---------------------------------------------------------

    @property
    def num_free_pages(self) -> int:
        return len(self._free) + len(self._evictable)

    @property
    def num_used_pages(self) -> int:
        return (self.config.num_pages - 1) - self.num_free_pages

    def usage_perc(self) -> float:
        total = self.config.num_pages - 1
        return self.num_used_pages / total if total else 0.0

    def prefix_hit_rate(self) -> float:
        if self.prefix_query_tokens == 0:
            return 0.0
        return self.prefix_hit_tokens / self.prefix_query_tokens

    # ---- low-level page ops ----------------------------------------------

    def _pop_free_page(self) -> int:
        if self._free:
            page_id = self._free.pop()
        elif self._evictable:
            page_id, _ = self._evictable.popitem(last=False)  # LRU
            info = self._pages.pop(page_id)
            if info.page_hash is not None:
                self._hash_to_page.pop(info.page_hash, None)
                if self.evict_listener is not None:
                    try:
                        self.evict_listener(page_id, info.page_hash)
                    except Exception as e:  # offload is best-effort
                        logger.warning("KV evict listener failed: %s", e)
        else:
            raise OutOfPagesError("KV cache out of pages")
        self._pages[page_id] = PageInfo(page_id=page_id, ref_count=1)
        return page_id

    def _release_page(self, page_id: int) -> None:
        info = self._pages[page_id]
        info.ref_count -= 1
        if info.ref_count > 0:
            return
        if info.page_hash is not None and self.config.enable_prefix_caching:
            # Keep content for future prefix hits.
            self._evictable[page_id] = None
            self._evictable.move_to_end(page_id)
        else:
            del self._pages[page_id]
            self._free.append(page_id)

    def _revive_page(self, page_id: int) -> None:
        """Take a zero-ref cached page back into active use."""
        self._evictable.pop(page_id, None)
        self._pages[page_id].ref_count += 1

    # ---- sequence-facing API ---------------------------------------------

    @staticmethod
    def chain_hashes(token_ids: Sequence[int],
                     page_size: int,
                     root: int = 0) -> List[PageHash]:
        """Content hashes for each *full* page of a token prefix.

        ``root`` seeds the chain's first parent. It namespaces cache
        identity beyond token content — the engine passes the
        sequence's cache salt (Sequence.cache_salt), which is nonzero
        for LoRA-adapter requests: adapter deltas on wk/wv make the
        KV bytes adapter-specific, so a base-model prompt must never
        hit pages prefilled through an adapter (and vice versa).
        """
        hashes: List[PageHash] = []
        parent = root
        for start in range(0, len(token_ids) - page_size + 1, page_size):
            chunk = tuple(token_ids[start:start + page_size])
            h: PageHash = (parent, chunk)
            hashes.append(h)
            parent = hash(h)
        return hashes

    def match_prefix(self, token_ids: Sequence[int],
                     root: int = 0) -> List[int]:
        """Longest chain of cached full pages matching the prompt prefix.

        Returns the page ids (ref-counted up; caller owns them).
        """
        if not self.config.enable_prefix_caching:
            # Don't count queries the cache never sees: inflating the
            # denominator here would drag the reported hit rate toward
            # zero on pods running with prefix caching disabled.
            return []
        self.prefix_query_tokens += len(token_ids)
        matched: List[int] = []
        # Never match the *entire* prompt: the final token must be
        # recomputed so prefill produces logits for sampling.
        usable = len(token_ids) - 1
        for page_hash in self.chain_hashes(token_ids[:usable],
                                           self.page_size, root):
            page_id = self._hash_to_page.get(page_hash)
            if page_id is None:
                break
            self._revive_page(page_id)
            matched.append(page_id)
        self.prefix_hit_tokens += len(matched) * self.page_size
        return matched

    def allocate_pages(self, n: int) -> List[int]:
        """n fresh (private, unhashed) pages for a sequence."""
        if n > self.num_free_pages:
            raise OutOfPagesError(
                f"Need {n} pages, only {self.num_free_pages} free"
            )
        return [self._pop_free_page() for _ in range(n)]

    def commit_full_pages(self, token_ids: Sequence[int],
                          pages: List[int],
                          already_hashed: int,
                          root: int = 0) -> None:
        """Register content hashes for pages that have become full.

        Args:
          token_ids: the sequence's tokens written so far
          pages: the sequence's page list (matched + private)
          already_hashed: count of leading pages already registered
        """
        if not self.config.enable_prefix_caching:
            return
        hashes = self.chain_hashes(token_ids, self.page_size, root)
        for i in range(already_hashed, min(len(hashes), len(pages))):
            page_id = pages[i]
            info = self._pages.get(page_id)
            if info is None or info.page_hash is not None:
                continue
            existing = self._hash_to_page.get(hashes[i])
            if existing is None:
                info.page_hash = hashes[i]
                self._hash_to_page[hashes[i]] = page_id
            # If another page already owns this hash we simply leave this
            # page private; dedup happens for future sequences.

    def register_restored_page(self, page_id: int,
                               page_hash: PageHash) -> None:
        """A page restored from an offload tier becomes a cached,
        hash-addressable page (future prompts hit it in HBM)."""
        info = self._pages.get(page_id)
        if info is None or info.page_hash is not None:
            return
        if page_hash not in self._hash_to_page:
            info.page_hash = page_hash
            self._hash_to_page[page_hash] = page_id

    def free_sequence(self, pages: List[int]) -> None:
        for page_id in pages:
            self._release_page(page_id)
