"""Guided JSON decoding: a byte-level automaton compiled to device
tables, enforced INSIDE the sampling step.

OpenAI ``response_format: {"type": "json_object"}`` (vLLM: guided
decoding). The constraint machine is a depth-bounded JSON DFA over
BYTES — states are (mode, container-stack) pairs discovered by BFS
from the start state, compiled to two dense tables:

  transition [n_states, vocab] int32  next state (-1 = disallowed)
  mask       [n_states, vocab] bool   token admissible from state

On device the per-row automaton state rides the decode-burst scan
carry: each step gathers ``mask[state]`` ([B, vocab]) to -inf the
disallowed logits and advances ``state = transition[state, token]`` —
no host round-trip, so constrained rows run at full burst speed
(model_runner). The host mirrors transitions with the same table
(``advance``) to track state across dispatches.

Scope: tokenizers whose ids ARE bytes (the Byte/Bench tokenizers —
ids 0-255 map to bytes; everything else is masked out except EOS,
which is admissible only in the DONE state). HF subword tokenizers
need per-token byte-string admission (an outlines-style vocabulary
DFA product) — rejected loudly at the server (server.py), not
silently misconstrained.

The reference stack's guided decoding is a vLLM pass-through
(engine-side feature); this is the TPU-native equivalent for the
built-in engine.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

WS = tuple(b" \t\n\r")
DIGITS = tuple(b"0123456789")
HEX = tuple(b"0123456789abcdefABCDEF")
# String-body bytes: anything printable-ish except '"' and '\\';
# control bytes (< 0x20) are invalid inside JSON strings. Non-ASCII
# UTF-8 continuation/lead bytes are allowed (the automaton does not
# validate UTF-8 sequences — the decoded text may contain replacement
# characters with random weights, but the JSON STRUCTURE is valid).
STR_BYTES = tuple(b for b in range(0x20, 256) if b not in (0x22, 0x5C))

# Modes (stack-independent part of a state).
(START, EXP_KEY_OR_CLOSE, EXP_KEY, KEY_STR, KEY_ESC, KEY_U1, KEY_U2,
 KEY_U3, KEY_U4, EXP_COLON, EXP_VALUE, EXP_VAL_OR_CLOSE, VAL_STR,
 VAL_ESC, VAL_U1, VAL_U2, VAL_U3, VAL_U4, AFTER_VALUE, NUM_MINUS,
 NUM_ZERO, NUM_INT, NUM_DOT, NUM_FRAC, NUM_E, NUM_EXP_SIGNED,
 NUM_EXP, LIT, DONE) = range(29)

_LITERALS = (b"true", b"false", b"null")


class JsonByteFsm:
    """Depth-bounded JSON automaton over bytes, with dense tables.

    A state is (mode, stack, lit_rest): ``stack`` is a tuple of
    b'{'/b'[' container markers (len <= max_depth), ``lit_rest`` the
    remaining bytes of an in-flight true/false/null literal. States
    are interned ints in discovery order; state 0 is START.
    """

    # Table width: bytes 0-255 + bos/eos specials. Every id >= 258 is
    # inadmissible by construction (byte-range tokenizer contract), so
    # the dense tables stop there — [n_states, vocab] at a 32k bench
    # vocab would cost ~300 MB for columns that are uniformly -1; the
    # runner pads the gathered mask rows back to vocab width.
    TABLE_WIDTH = 258

    def __init__(self, vocab_size: int, eos_token_id: int,
                 max_depth: int = 6):
        self.vocab_size = vocab_size
        self.eos_token_id = eos_token_id
        self.max_depth = max_depth
        assert eos_token_id is None or eos_token_id < self.TABLE_WIDTH
        width = min(vocab_size, self.TABLE_WIDTH)
        self._ids: Dict[tuple, int] = {}
        self._work: list = []
        start = self._intern((START, (), b""))
        assert start == 0
        trans_rows = []
        while self._work:
            key = self._work.pop(0)
            trans_rows.append(self._row(key))
        n = len(self._ids)
        self.transition = np.full((n, width), -1, np.int32)
        for i, row in enumerate(trans_rows):
            for tok, nxt in row.items():
                self.transition[i, tok] = nxt
        self.mask = self.transition >= 0

    # -- state construction --------------------------------------------------

    def _intern(self, key: tuple) -> int:
        if key not in self._ids:
            self._ids[key] = len(self._ids)
            self._work.append(key)
        return self._ids[key]

    def _row(self, key: tuple) -> Dict[int, int]:
        """byte/token -> next state id for one state."""
        mode, stack, lit = key
        out: Dict[int, int] = {}

        def to(b: int, mode2, stack2=None, lit2=b""):
            out[b] = self._intern(
                (mode2, stack if stack2 is None else stack2, lit2))

        def ws_self():
            for b in WS:
                to(b, mode, lit2=lit)

        def close_container(b_close: int):
            """'}' or ']' closing the innermost container."""
            want = 0x7D if stack[-1] == 0x7B else 0x5D
            if b_close != want:
                return
            popped = stack[:-1]
            if not popped:
                to(b_close, DONE, popped)
            else:
                to(b_close, AFTER_VALUE, popped)

        def open_value(b: int):
            """Transitions a value-start byte out of EXP_VALUE."""
            if b == 0x22:
                to(b, VAL_STR)
            elif b == 0x2D:
                to(b, NUM_MINUS)
            elif b == 0x30:
                to(b, NUM_ZERO)
            elif b in DIGITS:
                to(b, NUM_INT)
            elif b in (0x74, 0x66, 0x6E):  # t / f / n
                word = {0x74: b"true", 0x66: b"false",
                        0x6E: b"null"}[b]
                to(b, LIT, lit2=word[1:])
            elif b == 0x7B and len(stack) < self.max_depth:
                to(b, EXP_KEY_OR_CLOSE, stack + (0x7B,))
            elif b == 0x5B and len(stack) < self.max_depth:
                to(b, EXP_VAL_OR_CLOSE, stack + (0x5B,))

        def value_done():
            """State reached after a complete value: depends on the
            innermost container (objects expect , or }, arrays , or
            ])."""
            return (DONE, ()) if not stack else (AFTER_VALUE, stack)

        def number_delims():
            """A number is 'done' at any delimiter its context
            allows: whitespace/comma/close route as AFTER_VALUE."""
            m2, st2 = value_done()
            if m2 == DONE:
                for b in WS:
                    to(b, DONE, ())
                return
            for b in WS:
                to(b, AFTER_VALUE)
            for b, row_mode in self._after_value_bytes(stack):
                out[b] = row_mode

        if mode == START:
            ws_self()
            to(0x7B, EXP_KEY_OR_CLOSE, (0x7B,))
        elif mode == EXP_KEY_OR_CLOSE:
            ws_self()
            to(0x22, KEY_STR)
            close_container(0x7D)
        elif mode == EXP_KEY:
            ws_self()
            to(0x22, KEY_STR)
        elif mode in (KEY_STR, VAL_STR):
            esc = KEY_ESC if mode == KEY_STR else VAL_ESC
            for b in STR_BYTES:
                to(b, mode, lit2=lit)
            to(0x5C, esc)
            if mode == KEY_STR:
                to(0x22, EXP_COLON)
            else:
                m2, st2 = value_done()
                to(0x22, m2, st2)
        elif mode in (KEY_ESC, VAL_ESC):
            back = KEY_STR if mode == KEY_ESC else VAL_STR
            u1 = KEY_U1 if mode == KEY_ESC else VAL_U1
            for b in b'"\\/bfnrt':
                to(b, back)
            to(0x75, u1)  # \uXXXX
        elif mode in (KEY_U1, KEY_U2, KEY_U3, VAL_U1, VAL_U2, VAL_U3):
            for b in HEX:
                to(b, mode + 1)
        elif mode in (KEY_U4, VAL_U4):
            back = KEY_STR if mode == KEY_U4 else VAL_STR
            for b in HEX:
                to(b, back)
        elif mode == EXP_COLON:
            ws_self()
            to(0x3A, EXP_VALUE)
        elif mode == EXP_VALUE:
            ws_self()
            for b in (0x22, 0x2D, 0x7B, 0x5B) + DIGITS + (
                    0x74, 0x66, 0x6E):
                open_value(b)
        elif mode == EXP_VAL_OR_CLOSE:
            ws_self()
            for b in (0x22, 0x2D, 0x7B, 0x5B) + DIGITS + (
                    0x74, 0x66, 0x6E):
                open_value(b)
            close_container(0x5D)
        elif mode == AFTER_VALUE:
            ws_self()
            for b, nxt in self._after_value_bytes(stack):
                out[b] = nxt
        elif mode == NUM_MINUS:
            to(0x30, NUM_ZERO)
            for b in DIGITS[1:]:
                to(b, NUM_INT)
        elif mode in (NUM_ZERO, NUM_INT, NUM_FRAC, NUM_EXP):
            if mode == NUM_INT:
                for b in DIGITS:
                    to(b, NUM_INT)
            if mode == NUM_FRAC:
                for b in DIGITS:
                    to(b, NUM_FRAC)
            if mode == NUM_EXP:
                for b in DIGITS:
                    to(b, NUM_EXP)
            if mode in (NUM_ZERO, NUM_INT):
                to(0x2E, NUM_DOT)
            if mode != NUM_EXP:
                to(0x65, NUM_E)
                to(0x45, NUM_E)
            number_delims()
        elif mode == NUM_DOT:
            for b in DIGITS:
                to(b, NUM_FRAC)
        elif mode == NUM_E:
            to(0x2B, NUM_EXP_SIGNED)
            to(0x2D, NUM_EXP_SIGNED)
            for b in DIGITS:
                to(b, NUM_EXP)
        elif mode == NUM_EXP_SIGNED:
            for b in DIGITS:
                to(b, NUM_EXP)
        elif mode == LIT:
            nxt_b = lit[0]
            rest = lit[1:]
            if rest:
                to(nxt_b, LIT, lit2=rest)
            else:
                m2, st2 = value_done()
                to(nxt_b, m2, st2)
        elif mode == DONE:
            ws_self()
            if self.eos_token_id is not None:
                out[self.eos_token_id] = self._intern((DONE, (), b""))
        return out

    def _after_value_bytes(self, stack) -> list:
        """(byte, next_state_id) continuations after a complete value
        inside ``stack``'s innermost container."""
        res = []
        if not stack:
            return res
        if stack[-1] == 0x7B:
            res.append((0x2C, self._intern((EXP_KEY, stack, b""))))
            popped = stack[:-1]
            res.append((0x7D, self._intern(
                (DONE, (), b"") if not popped
                else (AFTER_VALUE, popped, b""))))
        else:
            res.append((0x2C, self._intern((EXP_VALUE, stack, b""))))
            popped = stack[:-1]
            res.append((0x5D, self._intern(
                (DONE, (), b"") if not popped
                else (AFTER_VALUE, popped, b""))))
        return res

    # -- host-side mirror ----------------------------------------------------

    def advance(self, state: int, token: int) -> int:
        """Host-side transition (same table the device gathers);
        ids beyond the table width are inadmissible."""
        if token >= self.transition.shape[1]:
            return -1
        return int(self.transition[state, token])


def build_json_fsm(tokenizer, max_depth: int = 6) -> JsonByteFsm:
    """Build the automaton for a byte-range tokenizer.

    Requires ids 0-255 to BE the UTF-8 bytes (ByteTokenizer /
    BenchTokenizer contract); every other id is inadmissible except
    EOS (DONE state only)."""
    return JsonByteFsm(tokenizer.vocab_size, tokenizer.eos_token_id,
                       max_depth=max_depth)
