"""Multi-LoRA adapter serving.

The reference enables LoRA by passing ``--enable-lora`` to vLLM
(helm/templates/deployment-vllm-multi.yaml:66-68, helm/values.yaml:56-58)
and serves adapters under their own model names (tutorials/08-lora.md
flow). Here LoRA is TPU-first: all adapter slots live in HBM as stacked
arrays ``A: [L, S, in, r]`` / ``B: [L, S, r, out]`` (L = layers, S =
slots), and a batch row selects its adapter with a gather on a per-row
id vector — one einsum pair per projection, fully static shapes, no
per-adapter dispatch. Slot 0 is all-zeros (the base model), so mixed
base/adapter batches run in the same compiled step.

Adapter files use the HF PEFT format (``adapter_config.json`` +
``adapter_model.safetensors`` with ``...layers.{i}.<proj>.lora_A.weight``
keys); ranks below ``max_lora_rank`` are zero-padded so every adapter
fits the static stack shape.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from production_stack_tpu.engine.config import ModelConfig
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

# PEFT module name -> our stacked-param name, per architecture.
_TARGET_MAP = {
    "llama": {
        "q_proj": "wq", "k_proj": "wk", "v_proj": "wv", "o_proj": "wo",
        "gate_proj": "w_gate", "up_proj": "w_up", "down_proj": "w_down",
    },
    "opt": {
        "q_proj": "wq", "k_proj": "wk", "v_proj": "wv",
        "out_proj": "wo", "fc1": "fc1", "fc2": "fc2",
    },
    "mixtral": {
        "q_proj": "wq", "k_proj": "wk", "v_proj": "wv",
        "o_proj": "wo",
    },
    # GPT-2 fuses q/k/v into c_attn; handled specially in
    # load_peft_adapter (A is shared, B is split three ways).
    "gpt2": {
        "attn.c_proj": "wo", "c_fc": "fc1", "mlp.c_proj": "fc2",
    },
}


def target_shapes(config: ModelConfig) -> Dict[str, Tuple[int, int]]:
    """(in_dim, out_dim) of every LoRA-targetable projection."""
    h = config.hidden_size
    nh, nkv, d = (config.num_attention_heads,
                  config.num_key_value_heads, config.head_dim)
    ffn = config.intermediate_size
    if config.architecture in ("opt", "gpt2"):
        return {
            "wq": (h, nh * d), "wk": (h, nh * d), "wv": (h, nh * d),
            "wo": (nh * d, h), "fc1": (h, ffn), "fc2": (ffn, h),
        }
    if config.architecture == "mixtral":
        # Expert weights are not LoRA targets; attention only.
        return {
            "wq": (h, nh * d), "wk": (h, nkv * d), "wv": (h, nkv * d),
            "wo": (nh * d, h),
        }
    return {
        "wq": (h, nh * d), "wk": (h, nkv * d), "wv": (h, nkv * d),
        "wo": (nh * d, h), "w_gate": (h, ffn), "w_up": (h, ffn),
        "w_down": (ffn, h),
    }


# Row-parallel projections (input dim sharded over 'tp', closed by a
# psum) across every supported family; everything else LoRA targets is
# column-parallel (output dim sharded).
ROW_PARALLEL_TARGETS = ("wo", "w_down", "fc2")


def lora_stack_specs(lora_ab, leading_axis, on_mesh):
    """PartitionSpecs for the adapter stacks inside a tp shard_map.

    The ONE definition of how LoRA shards under tensor parallelism,
    shared by the pp and sp serving bodies (parallel/
    {pipeline,context}_serving.py): each target shards like its base
    projection —
      column-parallel: x replicated -> A replicated, B column-sharded
        [L, S, r, out/tp] to match the projection's local out;
      row-parallel:    x arrives with a LOCAL input shard -> A
        row-sharded [L, S, in/tp, r] so x@A is a partial [.., r], B
        replicated; the caller's psum sums base + delta partials.

    Args:
      lora_ab:      {"a": {target: ...}, "b": {target: ...}} stacks
      leading_axis: mesh axis name sharding the stacks' L axis
                    ("pp"), or None (sp: layers replicated)
      on_mesh:      callable dropping axis names the mesh lacks
                    (parallel/mesh.py _on_mesh partial) — degrades
                    every spec to the leading axis alone on tp-less
                    meshes
    """
    from jax.sharding import PartitionSpec as P

    lead = leading_axis
    return {
        "a": {tgt: on_mesh(P(lead, None, "tp", None)
                           if tgt in ROW_PARALLEL_TARGETS
                           else P(lead))
              for tgt in lora_ab["a"]},
        "b": {tgt: on_mesh(P(lead, None, None, "tp")
                           if tgt not in ROW_PARALLEL_TARGETS
                           else P(lead))
              for tgt in lora_ab["b"]},
    }


@dataclasses.dataclass
class LoRAAdapter:
    """One loaded adapter: per-target (A [L, in, r], B [L, r, out])."""

    name: str
    rank: int
    scaling: float
    # target name -> (A, B) numpy arrays, already rank-padded.
    weights: Dict[str, Tuple[np.ndarray, np.ndarray]]


def empty_lora_stack(config: ModelConfig, max_loras: int,
                     max_lora_rank: int) -> Dict:
    """All-zero adapter stacks (slot 0 stays zero forever = base)."""
    slots = max_loras + 1
    layers = config.num_hidden_layers
    dtype = config.jax_dtype
    a, b = {}, {}
    for tgt, (d_in, d_out) in target_shapes(config).items():
        a[tgt] = jnp.zeros((layers, slots, d_in, max_lora_rank), dtype)
        b[tgt] = jnp.zeros((layers, slots, max_lora_rank, d_out), dtype)
    return {
        "a": a, "b": b,
        "scaling": jnp.zeros((slots,), jnp.float32),
    }


@jax.jit
def _set_slot(stack_arr: jax.Array, slot: jax.Array,
              value: jax.Array) -> jax.Array:
    return stack_arr.at[:, slot].set(value.astype(stack_arr.dtype))


def install_adapter(stack: Dict, slot: int,
                    adapter: LoRAAdapter) -> Dict:
    """Write one adapter into a stack slot (out-of-place pytree).

    Targets the adapter does not train are zeroed, so re-registering a
    name never leaves stale weights from the slot's previous occupant.
    """
    for tgt in adapter.weights:
        if tgt not in stack["a"]:
            raise ValueError(f"Unknown LoRA target {tgt!r}")
    a = dict(stack["a"])
    b = dict(stack["b"])
    slot_arr = jnp.asarray(slot)
    for tgt in a:
        pair = adapter.weights.get(tgt)
        if pair is None:
            zero_a = jnp.zeros(a[tgt].shape[0:1] + a[tgt].shape[2:],
                               a[tgt].dtype)
            zero_b = jnp.zeros(b[tgt].shape[0:1] + b[tgt].shape[2:],
                               b[tgt].dtype)
            a[tgt] = _set_slot(a[tgt], slot_arr, zero_a)
            b[tgt] = _set_slot(b[tgt], slot_arr, zero_b)
        else:
            a[tgt] = _set_slot(a[tgt], slot_arr, jnp.asarray(pair[0]))
            b[tgt] = _set_slot(b[tgt], slot_arr, jnp.asarray(pair[1]))
    scaling = stack["scaling"].at[slot].set(adapter.scaling)
    return {"a": a, "b": b, "scaling": scaling}


def lora_matmul(x: jnp.ndarray, base_w, lora_layer: Optional[Dict],
                target: str, lora_ids: Optional[jnp.ndarray],
                scale: Optional[jnp.ndarray]) -> jnp.ndarray:
    """``x @ W + scale_b * (x @ A[id_b]) @ B[id_b]`` per batch row.

    ``base_w`` is either a dense matrix or an int8 (weight, scale)
    pair (engine/quantization.py). Inside ``lax.scan`` the stacks
    arrive with the layer axis already sliced off:
    ``lora_layer['a'][target]`` is [S, in, r]. The gather over
    ``lora_ids`` keeps shapes static for any adapter mix.
    """
    if isinstance(base_w, tuple):
        from production_stack_tpu.engine.quantization import (
            dequant_matmul,
        )
        out = dequant_matmul(x, base_w)
    else:
        out = x @ base_w
    if lora_layer is None:
        return out
    a_sel = lora_layer["a"][target][lora_ids]  # [B, in, r]
    b_sel = lora_layer["b"][target][lora_ids]  # [B, r, out]
    delta = jnp.einsum("bti,bir->btr", x, a_sel)
    delta = jnp.einsum("btr,bro->bto", delta, b_sel)
    return out + delta * scale[:, None, None].astype(x.dtype)


def load_peft_adapter(path: str, config: ModelConfig,
                      max_lora_rank: int,
                      name: Optional[str] = None) -> LoRAAdapter:
    """Load a HuggingFace PEFT adapter directory.

    Expects ``adapter_config.json`` (r, lora_alpha, target_modules) and
    ``adapter_model.safetensors`` (or ``.npz`` fallback) with keys
    ``...model.layers.{i}.self_attn.q_proj.lora_A.weight`` of shape
    [r, in] (A) and [out, r] (B) — transposed here to row-major matmul
    layout and zero-padded to ``max_lora_rank``.
    """
    cfg_path = os.path.join(path, "adapter_config.json")
    with open(cfg_path) as f:
        acfg = json.load(f)
    rank = int(acfg["r"])
    alpha = float(acfg.get("lora_alpha", rank))
    if rank > max_lora_rank:
        raise ValueError(
            f"Adapter rank {rank} exceeds --max-lora-rank {max_lora_rank}"
        )
    st_path = os.path.join(path, "adapter_model.safetensors")
    if os.path.exists(st_path):
        from safetensors.numpy import load_file
        raw = load_file(st_path)
    else:
        npz = np.load(os.path.join(path, "adapter_model.npz"))
        raw = {k: npz[k] for k in npz.files}

    tmap = _TARGET_MAP.get(config.architecture, _TARGET_MAP["llama"])
    layers = config.num_hidden_layers
    shapes = target_shapes(config)
    per_target: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}

    def find(template: str, i: int, proj: str, kind: str):
        for key in raw:
            # Llama/OPT name layers "...layers.{i}."; GPT-2 "...h.{i}.".
            if ((f"layers.{i}." in key or f"h.{i}." in key)
                    and f"{proj}." in key and f"lora_{kind}" in key):
                return raw[key]
        return None

    for proj, tgt in tmap.items():
        d_in, d_out = shapes[tgt]
        a_stack = np.zeros((layers, d_in, max_lora_rank), np.float32)
        b_stack = np.zeros((layers, max_lora_rank, d_out), np.float32)
        found = False
        for i in range(layers):
            A = find("", i, proj, "A")  # [r, in]
            B = find("", i, proj, "B")  # [out, r]
            if A is None or B is None:
                continue
            found = True
            r = A.shape[0]
            a_stack[i, :, :r] = np.asarray(A, np.float32).T
            b_stack[i, :r, :] = np.asarray(B, np.float32).T
        if found:
            per_target[tgt] = (a_stack, b_stack)

    if config.architecture == "gpt2":
        # GPT-2's q/k/v live in one fused c_attn [h, 3h] projection.
        # PEFT trains a single (A [r, h], B [3h, r]) pair for it; we
        # split B into thirds so each of wq/wk/wv gets (A, B_chunk) —
        # the low-rank update decomposes exactly because the three
        # outputs are disjoint column blocks of c_attn.
        h = config.hidden_size
        a_stack = np.zeros((layers, h, max_lora_rank), np.float32)
        b_stacks = {t: np.zeros((layers, max_lora_rank, h), np.float32)
                    for t in ("wq", "wk", "wv")}
        found = False
        for i in range(layers):
            A = find("", i, "c_attn", "A")  # [r, h]
            B = find("", i, "c_attn", "B")  # [3h, r]
            if A is None or B is None:
                continue
            found = True
            r = A.shape[0]
            a_stack[i, :, :r] = np.asarray(A, np.float32).T
            Bf = np.asarray(B, np.float32)
            for j, t in enumerate(("wq", "wk", "wv")):
                b_stacks[t][i, :r, :] = Bf[j * h:(j + 1) * h, :].T
        if found:
            for t in ("wq", "wk", "wv"):
                per_target[t] = (a_stack, b_stacks[t])

    if not per_target:
        raise ValueError(f"No LoRA weights found under {path}")
    return LoRAAdapter(
        name=name or os.path.basename(os.path.normpath(path)),
        rank=rank,
        scaling=alpha / rank,
        weights=per_target,
    )


class LoRARegistry:
    """Name -> slot bookkeeping over the device-resident stack."""

    def __init__(self, config: ModelConfig, max_loras: int,
                 max_lora_rank: int):
        self.config = config
        self.max_loras = max_loras
        self.max_lora_rank = max_lora_rank
        self.stack = empty_lora_stack(config, max_loras, max_lora_rank)
        self.slots: Dict[str, int] = {}
        # Per-slot prefix-cache namespace roots: adapter KV (wk/wv
        # carry the deltas) must never cross-hit base or other-adapter
        # pages, and RE-registering a name with new weights must not
        # hit its own stale pages — in HBM or in a persistent remote
        # offload tier across restarts (content-addressed, see
        # register()).
        self._cache_roots: Dict[int, int] = {}

    def register(self, adapter: LoRAAdapter) -> int:
        if adapter.name in self.slots:
            slot = self.slots[adapter.name]
        else:
            if len(self.slots) >= self.max_loras:
                raise ValueError(
                    f"All {self.max_loras} LoRA slots in use"
                )
            slot = len(self.slots) + 1  # slot 0 = base
        # Install before committing the name->slot mapping: if the
        # adapter targets a projection this architecture doesn't
        # expose, the name must not stay registered against an
        # all-zero slot (which would silently serve the base model).
        self.stack = install_adapter(self.stack, slot, adapter)
        self.slots[adapter.name] = slot
        # Content-addressed: the namespace is a digest of the actual
        # adapter weights, so (a) re-registering identical weights
        # keeps prefix-cache/offload reuse, (b) NEW weights under the
        # same name get a fresh namespace even across process restarts
        # against a persistent remote KV tier (a process-local counter
        # would collide there).
        import hashlib
        h = hashlib.sha256(f"lora:{adapter.name}".encode())
        for tgt in sorted(adapter.weights):
            a, b = adapter.weights[tgt]
            h.update(tgt.encode())
            h.update(np.ascontiguousarray(a).tobytes())
            h.update(np.ascontiguousarray(b).tobytes())
        h.update(repr(adapter.scaling).encode())
        self._cache_roots[slot] = int.from_bytes(h.digest()[:8], "big")
        logger.info("LoRA adapter %r installed in slot %d (rank %d)",
                    adapter.name, slot, adapter.rank)
        return slot

    def slot_for(self, name: Optional[str]) -> int:
        if name is None:
            return 0
        return self.slots[name]

    def cache_root(self, slot: int) -> int:
        """Prefix-cache chain root for a slot (0 = base namespace)."""
        if slot == 0:
            return 0
        return self._cache_roots[slot]

    def names(self) -> List[str]:
        return list(self.slots)
