"""Draft-free speculative decoding: the prompt-lookup proposer.

Prompt lookup (Saxena; the n-gram member of the speculative-decoding
family, Leviathan et al.) drafts continuation tokens from the
sequence's OWN history: if the trailing ``min_match``-gram of
prompt + output has occurred before, the tokens that followed that
occurrence are proposed as drafts. No second model, no extra HBM —
ideal for the multi-round-QA serving shape (bench.py) where answers
quote prompts and follow-ups replay history.

The proposer is pure host-side bookkeeping; verification happens in
one fixed-shape device program (model_runner._spec_verify_impl) and
the acceptance rule in ops/sampling.spec_verify keeps the output
distribution exactly the target model's (docs/speculative.md).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from production_stack_tpu.engine.sequence import Sequence


class _SeqIndex:
    """Incremental n-gram index over one sequence's token history.

    Maps every ``min_match``-gram to the positions where it starts
    (ascending). Tokens are only ever appended (preemption folds
    outputs into the prompt but leaves all_token_ids unchanged), so
    the index extends monotonically and never rebuilds.
    """

    __slots__ = ("grams", "indexed")

    def __init__(self):
        self.grams: Dict[Tuple[int, ...], List[int]] = {}
        self.indexed = 0  # grams starting before this position exist

    def extend(self, tokens: List[int], min_match: int) -> None:
        end = len(tokens) - min_match + 1
        for i in range(self.indexed, max(self.indexed, end)):
            self.grams.setdefault(
                tuple(tokens[i:i + min_match]), []).append(i)
        self.indexed = max(self.indexed, end)


class NgramProposer:
    """Per-sequence prompt-lookup draft proposer.

    ``propose`` returns up to ``max_len`` draft tokens: the
    continuation of the best prior occurrence of the sequence's
    trailing ``min_match``-gram, preferring the LONGEST backward
    match (max-match) and breaking ties toward the most recent
    occurrence (recency tracks the current topic).
    """

    # Occurrence scan cap per proposal: pathological histories (e.g. a
    # constant token) index O(len) positions for one gram; scoring all
    # of them would make proposal O(len^2) over a generation.
    MAX_CANDIDATES = 32
    # Backward max-match score cap: a periodic history lets the
    # backward scan run arbitrarily far (every candidate matches the
    # whole loop), and match length beyond a short context adds no
    # ranking signal. The first candidate (most recent) to hit the
    # cap cannot be beaten, so the scan also short-circuits there.
    MAX_BACKWARD = 16

    def __init__(self, k: int, min_match: int = 2):
        if k < 1:
            raise ValueError("speculative k must be >= 1")
        if min_match < 1:
            raise ValueError("speculative min_match must be >= 1")
        self.k = k
        self.min_match = min_match
        self._index: Dict[str, _SeqIndex] = {}

    def propose(self, seq: Sequence, max_len: int) -> List[int]:
        """Draft tokens for ``seq``'s next positions (possibly [])."""
        max_len = min(max_len, self.k)
        if max_len <= 0:
            return []
        tokens = seq.all_token_ids
        n = len(tokens)
        if n < self.min_match + 1:
            return []
        idx = self._index.setdefault(seq.seq_id, _SeqIndex())
        idx.extend(tokens, self.min_match)
        tail_start = n - self.min_match
        hits = idx.grams.get(tuple(tokens[tail_start:]))
        if not hits:
            return []
        best_start, best_score = -1, 0
        # Most-recent first so ties resolve toward recency; skip the
        # tail's own occurrence (it has no continuation).
        scanned = 0
        for i in reversed(hits):
            if i >= tail_start:
                continue
            if scanned >= self.MAX_CANDIDATES:
                break
            scanned += 1
            # Max-match: extend the guaranteed min_match-gram match
            # backwards; a longer shared context predicts better.
            score, j = self.min_match, 1
            while (score < self.MAX_BACKWARD and i - j >= 0
                   and tokens[i - j] == tokens[tail_start - j]):
                score += 1
                j += 1
            if score > best_score:
                best_start, best_score = i, score
            if score >= self.MAX_BACKWARD:
                break  # most recent capped match; nothing beats it
        if best_start < 0:
            return []
        cont = best_start + self.min_match
        # Periodic self-continuation: when the match overlaps the tail
        # (period = tail_start - best_start < max_len), the known
        # continuation runs out at n — but appending it makes the
        # virtual history end in the SAME gram one period later, so
        # the lookup would keep yielding the loop. Emitting the wrap
        # directly drafts full-length candidates for looping tails
        # (where speculation pays most) instead of one token per step.
        # cont + (t % period) <= tail_start + min_match - 1 = n - 1,
        # so every index is in range; for period >= max_len this is
        # exactly tokens[cont:cont + max_len].
        period = tail_start - best_start
        return [tokens[cont + (t % period)] for t in range(max_len)]

    def drop(self, seq_id: str) -> None:
        """Release a finished/aborted sequence's index."""
        self._index.pop(seq_id, None)
