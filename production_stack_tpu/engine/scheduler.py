"""Continuous-batching scheduler.

The TPU twist (SURVEY.md §7 "hard parts" (a)): vLLM's scheduler emits
dynamically-shaped batches because CUDA kernels launch per step; under
XLA every shape is a compiled program, so this scheduler plans work in
*fixed* shapes — prefill chunks padded to buckets, decode as a constant-
width slot batch — and the runner caches one executable per shape.

A step is either one prefill chunk (chunked prefill, reference flag
--enable-chunked-prefill, deployment-vllm-multi.yaml:69-71) or one
decode batch over all running sequences; the two alternate when both
have work so neither starves.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

from production_stack_tpu.engine.config import (
    CacheConfig,
    SchedulerConfig,
)
from production_stack_tpu.engine.kv_cache import (
    OutOfPagesError,
    PagedCacheManager,
)
from production_stack_tpu.engine.sequence import (
    FinishReason,
    Sequence,
    SequenceState,
    decode_budget,
)
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

# Sustained overload preempts on every planning pass; the per-victim
# warning is rate-limited to one line per interval (with a
# suppressed-count) so logging can't become the bottleneck.
_PREEMPT_LOG_INTERVAL_S = 5.0


@dataclass
class PrefillChunk:
    seq: Sequence
    chunk_start: int  # absolute position of first token in chunk
    chunk_tokens: List[int]
    is_last_chunk: bool


@dataclass
class PrefillPlan:
    """One batched prefill step: the next chunk of up to
    ``prefill_batch_size`` DISTINCT waiting sequences, padded to a
    fixed row count so the compiled program shape never varies.

    ``sp=True`` marks a context-parallel whole-prompt plan (a single
    sequence whose entire prompt prefills in one dispatch with the
    sequence sharded over the mesh's 'sp' axis —
    parallel/context_serving.py)."""

    chunks: List[PrefillChunk]
    sp: bool = False


@dataclass
class DecodePlan:
    seqs: List[Sequence]
    # Multi-step window for this dispatch (1 = single step). Decided
    # here so page-capacity reservation and the runner's compiled
    # program agree on the same lookahead.
    window: int = 1
    # Speculative verify step (docs/speculative.md): per-row draft
    # tokens parallel to ``seqs`` ([] = plain single-token row inside
    # the same fixed-shape program). None = normal decode.
    drafts: Optional[List[List[int]]] = None


@dataclass
class StepPlan:
    prefill: Optional[PrefillPlan] = None
    decode: Optional[DecodePlan] = None

    @property
    def empty(self) -> bool:
        return self.prefill is None and self.decode is None


class Scheduler:
    def __init__(self, config: SchedulerConfig, cache_config: CacheConfig,
                 cache_manager: PagedCacheManager,
                 sp_threshold: Optional[int] = None,
                 guided_advance=None):
        # Optional hook(seq, token) advancing a guided-decoding
        # automaton state as tokens are appended (engine/guided.py;
        # the engine binds it so host state mirrors the device carry).
        self.guided_advance = guided_advance
        self.config = config
        self.page_size = cache_config.page_size
        self.cache = cache_manager
        # Prompts >= this many tokens (first touch, no prefix hit)
        # take the context-parallel whole-prompt prefill path; None
        # disables it (engine sets this when --context-parallel-size
        # > 1).
        self.sp_threshold = sp_threshold
        self.waiting: Deque[Sequence] = deque()
        self.running: List[Sequence] = []
        self._last_was_prefill = False
        # Optional offload-tier restore hook:
        # (prompt_token_ids, matched_pages) -> extra restored page ids.
        self.restore_hook = None
        # Optional preempt-to-offload hook (docs/qos.md): seq -> count
        # of committed KV pages shipped to the offload tier before the
        # victim's pages are freed. None / 0 = classic
        # drop-and-recompute. Installed by the engine when an offload
        # tier is configured and qos.preempt_to_offload is on.
        self.evict_hook = None
        # vllm:preempt_offload_total{outcome}: "offloaded" victims had
        # their pages shipped; "recompute" victims fell back to the
        # classic full-prompt recompute.
        self.preempt_offload_outcomes: Dict[str, int] = {
            "offloaded": 0, "recompute": 0}
        self._preempt_log_ts = float("-inf")
        self._preempt_log_suppressed = 0
        # End-to-end tracing (docs/observability.md): mirror of
        # LLMEngine.tracer, installed via its setter; None = untraced.
        self.tracer = None
        # Sequences aborted by the scheduler itself (oversized prompts,
        # permanent cache starvation); the engine drains this to emit
        # terminal outputs to their clients.
        self.newly_aborted: List[Sequence] = []
        # Cumulative count of sequences preempted for KV-cache
        # pressure (vllm:num_preemptions_total parity).
        self.num_preemptions = 0
        # Draft-free speculative decoding (docs/speculative.md): the
        # prompt-lookup proposer drafts from each sequence's own
        # history; None when the feature is off.
        self.proposer = None
        if config.speculative_k > 0:
            from production_stack_tpu.engine.spec import NgramProposer
            self.proposer = NgramProposer(
                config.speculative_k, config.speculative_min_match)
        # Self-tuning knobs (docs/autotuning.md), both host-side
        # plan-time values — no compiled shape depends on either.
        # Prefill token budget a unified (mixed) step may admit;
        # defaults to a dedicated prefill step's full bandwidth.
        self.mixed_prefill_budget = (config.prefill_chunk_size
                                     * config.prefill_batch_size)
        # QoS degrade-ladder clamp: while set, non-interactive rows
        # (priority > 0) are planned spec-off, reserving draft/verify
        # slack for interactive traffic under overload.
        self.spec_degrade_clamp = False

    # ---- queue management -------------------------------------------------

    def add_sequence(self, seq: Sequence) -> None:
        if len(self.waiting) >= self.config.max_queue_len:
            seq.transition(SequenceState.ABORTED)
            seq.finish_reason = FinishReason.ABORT
            raise RuntimeError("Scheduler queue full")
        if seq.num_prompt_tokens >= self.config.max_model_len:
            seq.transition(SequenceState.ABORTED)
            seq.finish_reason = FinishReason.ABORT
            raise ValueError(
                f"Prompt is {seq.num_prompt_tokens} tokens but "
                f"max_model_len is {self.config.max_model_len}"
            )
        max_prompt_pages = (self.config.max_pages_per_seq(self.page_size)
                            * self.page_size)
        if seq.num_prompt_tokens >= min(
                max_prompt_pages,
                (self.cache.config.num_pages - 1) * self.page_size):
            seq.transition(SequenceState.ABORTED)
            seq.finish_reason = FinishReason.ABORT
            raise ValueError(
                f"Prompt of {seq.num_prompt_tokens} tokens cannot fit "
                "in the KV cache"
            )
        if seq.num_prompt_tokens + seq.sampling.max_tokens > \
                self.config.max_model_len:
            # Clamp generation to fit the model length budget.
            seq.sampling.max_tokens = max(
                1, self.config.max_model_len - seq.num_prompt_tokens
            )
        self.waiting.append(seq)

    def abort_sequence(self, seq: Sequence) -> None:
        self._finish(seq, FinishReason.ABORT)
        if seq in self.running:
            self.running.remove(seq)
        try:
            self.waiting.remove(seq)
        except ValueError:
            pass

    @property
    def num_waiting(self) -> int:
        # Includes AWAITING_KV handoffs: they occupy a queue slot and
        # belong in num_requests_waiting (docs/disaggregation.md).
        return len(self.waiting)

    @property
    def num_awaiting_kv(self) -> int:
        return sum(1 for s in self.waiting
                   if s.state == SequenceState.AWAITING_KV)

    def _has_plannable_waiting(self) -> bool:
        """Waiting work prefill could actually plan now — AWAITING_KV
        handoffs are parked until the engine admits them, so they must
        not trigger prefill planning or break the async pipeline."""
        return any(s.state != SequenceState.AWAITING_KV
                   for s in self.waiting)

    @property
    def num_running(self) -> int:
        return len(self.running)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    # ---- planning ---------------------------------------------------------

    def plan_step(self) -> StepPlan:
        want_prefill = bool(
            self._has_plannable_waiting()
            and len(self.running) < self.config.max_num_seqs
        )
        want_decode = bool(self.running)
        if self.config.unified_step and want_prefill and want_decode:
            # Unified ragged step (docs/unified_step.md): admit
            # prefill chunks INTO the decode step under a token
            # budget instead of alternating whole steps, so long
            # prompts never stall decode ITL. Falls through to the
            # bimodal alternation when a row needs per-token host
            # state the ragged program doesn't compile.
            plan = self._plan_mixed()
            if plan is not None and not plan.empty:
                return plan
        if want_prefill and want_decode:
            # Alternate so neither side starves.
            do_prefill = not self._last_was_prefill
        else:
            do_prefill = want_prefill
        if do_prefill:
            plan = self._plan_prefill()
            if plan is not None:
                self._last_was_prefill = True
                return StepPlan(prefill=plan)
            want_decode = bool(self.running)
        if want_decode:
            self._last_was_prefill = False
            if self.proposer is not None:
                plan = self._plan_spec()
                if plan is not None:
                    return StepPlan(decode=plan)
            window = self._decode_window()
            self._ensure_decode_capacity(window)
            if self.running:
                # Re-check: preemption may have changed who can take a
                # full window.
                window = min(window, self._decode_window())
                return StepPlan(decode=DecodePlan(
                    seqs=list(self.running), window=window))
        return StepPlan()

    def _plan_spec(self) -> Optional[DecodePlan]:
        """Plan one speculative verify step, or None to fall back to
        plain decode (no row drafted anything, or a row needs per-row
        device inputs the verify program doesn't compile). Exactly two
        decode-side programs ever compile: the S-wide verify and the
        decode_steps-window decode/burst the fallback uses."""
        for seq in self.running:
            sp = seq.sampling
            if (sp.needs_penalties or sp.seed is not None
                    or sp.logit_bias
                    or sp.min_tokens > seq.num_generated
                    or seq.fsm_state is not None):
                # Whole-step fallback: padding these rows through the
                # verify shape would need the penalty/seed/bias/
                # suppress/guided inputs compiled into it; the normal
                # decode path already serves them.
                return None
        drafts: Dict[str, List[int]] = {}
        for seq in self.running:
            if seq.spec_off or (self.spec_degrade_clamp
                                and seq.priority > 0):
                # QoS degradation (docs/qos.md): throttled-tenant rows
                # ride the verify step as plain single-token rows.
                continue
            # Cap so emitted tokens (accepted + bonus) never exceed
            # the row's budget — a draft the budget can't emit would
            # also write KV past max_model_len.
            d = self.proposer.propose(seq, self._draft_limit(seq))
            if d:
                drafts[seq.seq_id] = d
        if not drafts:
            return None
        # Hybrid profitability gate (docs/speculative.md
        # §interactions): a verify step displaces a decode_steps-deep
        # burst, and rows without drafts emit one token instead of
        # decode_steps. Take the spec step only when, at full
        # acceptance, it can emit at least as many tokens as the
        # burst it displaces (each row emits accepted+1, so the batch
        # emits <= sum(draft lens) + rows); otherwise defer — the
        # drafts regrow from the same history on a later step. With
        # decode_steps == 1 this always passes.
        window = max(1, self.config.decode_steps)
        if (sum(len(d) for d in drafts.values()) + len(self.running)
                < window * len(self.running)):
            return None
        # Reserve pages for 1 + draft_len tokens per row; preemption
        # inside the pass may shrink `running` (victims' drafts are
        # simply dropped with them).
        self._ensure_decode_capacity(per_seq={
            s.seq_id: 1 + len(drafts.get(s.seq_id, ()))
            for s in self.running})
        if not self.running:
            return None
        plan_drafts = [drafts.get(s.seq_id, [])
                       for s in self.running]
        if not any(plan_drafts):
            return None
        return DecodePlan(seqs=list(self.running), window=1,
                          drafts=plan_drafts)

    def _plan_mixed(self) -> Optional[StepPlan]:
        """Plan one unified ragged step: every running sequence as a
        decode row (with prompt-lookup drafts when the proposer has
        them — spec rows ride the same span the program already
        compiles) plus waiting prefill chunks admitted under a token
        budget matching a dedicated prefill step's full bandwidth
        (``prefill_chunk_size * prefill_batch_size``) — so admission
        under mixing proceeds exactly as fast as alternation would,
        while decode rows keep emitting instead of stalling. Returns
        None to fall back to bimodal alternation when any running
        row needs per-row device inputs the ragged program doesn't
        compile (same exclusion set as _plan_spec / plan_ahead)."""
        for seq in self.running:
            sp = seq.sampling
            if (sp.needs_penalties or sp.seed is not None
                    or sp.logit_bias
                    or sp.min_tokens > seq.num_generated
                    or seq.fsm_state is not None):
                return None
        drafts: Dict[str, List[int]] = {}
        if self.proposer is not None:
            for seq in self.running:
                if seq.spec_off or (self.spec_degrade_clamp
                                    and seq.priority > 0):
                    continue
                d = self.proposer.propose(seq,
                                          self._draft_limit(seq))
                if d:
                    drafts[seq.seq_id] = d
        # Reserve decode-side pages first (1 + draft_len per row);
        # preemption here shrinks `running` before prefill admission
        # competes for the same pages.
        self._ensure_decode_capacity(per_seq={
            s.seq_id: 1 + len(drafts.get(s.seq_id, ()))
            for s in self.running})
        if not self.running:
            return None
        prefill = self._plan_prefill(
            max_tokens=self.mixed_prefill_budget)
        if prefill is not None and prefill.sp:
            # Context-parallel whole-prompt plans run alone (their
            # dispatch shards the sequence over the mesh); the
            # decode rows keep their reserved pages for next step.
            self._last_was_prefill = True
            return StepPlan(prefill=prefill)
        plan_drafts = None
        if drafts:
            rows = [drafts.get(s.seq_id, []) for s in self.running]
            if any(rows):
                plan_drafts = rows
        if prefill is None and plan_drafts is None:
            # Nothing ragged about this step (prefill couldn't admit,
            # no drafts): let the bimodal path plan it — it knows how
            # to take a decode_steps burst.
            return None
        decode = DecodePlan(seqs=list(self.running), window=1,
                            drafts=plan_drafts)
        self._last_was_prefill = prefill is not None
        return StepPlan(prefill=prefill, decode=decode)

    def plan_ahead(self, inflight_rows) -> Optional[List[
            Optional[Sequence]]]:
        """Plan decode step N+1 while step N is still in flight
        (docs/async_pipeline.md): assume every running row commits
        exactly one token, pre-allocate the boundary pages that
        assumption needs, and return a row list ALIGNED to
        ``inflight_rows`` (None = slot masked: the row is gone or
        provably finishes when step N commits). The engine feeds step
        N's sampled-token device array straight into step N+1, so row
        slots must not shift.

        Returns None to break the pipeline (the engine then completes
        step N and re-plans synchronously with full knowledge):
        - prefill work is waiting and could admit (matches
          plan_step's want_prefill, so prefill never starves),
        - a row needs per-token host state the ahead plan would
          compute one token stale (penalties, seeded sampling,
          logit_bias, min_tokens suppression, guided decoding — the
          same exclusion set as _plan_spec),
        - boundary pages cannot be allocated (never preempt with a
          step in flight: the victim's pages are inputs of the
          running program).
        """
        if (self._has_plannable_waiting()
                and len(self.running) < self.config.max_num_seqs):
            return None
        rows: List[Optional[Sequence]] = []
        any_live = False
        for seq in inflight_rows:
            if seq is None or seq.state != SequenceState.RUNNING:
                rows.append(None)
                continue
            sp = seq.sampling
            if (sp.needs_penalties or sp.seed is not None
                    or sp.logit_bias
                    or sp.min_tokens > seq.num_generated + 1
                    or seq.fsm_state is not None):
                return None
            if self._seq_budget(seq) <= 1:
                # Step N's token exhausts the row's budget: it will
                # finish with reason=length at reconcile. Mask the
                # slot now — a live row here would write KV past the
                # row's page budget.
                rows.append(None)
                continue
            rows.append(seq)
            any_live = True
        if not any_live:
            return None
        for seq in rows:
            if seq is None:
                continue
            # Post-commit convention: before a decode step, capacity
            # covers total_len + 1 tokens; after step N commits,
            # total_len grows by one, so reserve total_len + 2 now.
            # The pages simply extend seq.pages — a finish/abort at
            # reconcile returns them through the ordinary
            # free_sequence path, no separate bookkeeping.
            needed = self._pages_needed(seq, seq.total_len + 2)
            if needed == 0:
                continue
            try:
                seq.pages.extend(self.cache.allocate_pages(needed))
            except OutOfPagesError:
                # Pages already granted to earlier rows stay with
                # them (they are those rows' legitimate next-step
                # reservation; the sync re-plan reuses them).
                return None
        self._last_was_prefill = False
        return rows

    def _decode_window(self) -> int:
        """The decode burst evaluates per-row budgets and stop sets on
        device (model_runner._decode_burst_impl), so the full window
        is always safe — rows with less than K remaining simply go
        inactive mid-burst. One decode shape compiles, ever."""
        return max(1, self.config.decode_steps)

    def _seq_budget(self, seq: Sequence) -> int:
        return decode_budget(seq, self.config.max_model_len)

    def _draft_limit(self, seq: Sequence) -> int:
        """Longest draft this row may carry: the emit budget, further
        capped per-sequence by the spec-k autotune controller
        (docs/autotuning.md). The cap only shortens the draft list —
        a non-shape input — so the compiled verify span is
        untouched."""
        limit = self._seq_budget(seq) - 1
        if seq.spec_k_cap is not None:
            limit = min(limit, seq.spec_k_cap)
        return limit

    def _plan_prefill(self, max_tokens: Optional[int] = None
                      ) -> Optional[PrefillPlan]:
        # ``max_tokens`` caps the total prompt tokens admitted this
        # step (unified ragged steps budget prefill work so decode
        # rows sharing the batch keep their ITL — _plan_mixed); the
        # final chunk is truncated to fit, resuming next step.
        chunks: List[PrefillChunk] = []
        tokens_planned = 0
        admitting = 0  # rows that will join `running` this step
        # QoS admission order (docs/qos.md): priority class first, then
        # arrival. The sort is stable and preempted victims keep their
        # original arrival_time, so a restored victim leads its class
        # rather than re-queueing at the back.
        for seq in sorted(self.waiting,
                          key=lambda s: (s.priority, s.arrival_time)):
            if len(chunks) >= self.config.prefill_batch_size:
                break
            if seq.state == SequenceState.ABORTED:
                self.waiting.remove(seq)
                continue
            if seq.state == SequenceState.AWAITING_KV:
                # Parked handoff: its KV pages are not reachable yet
                # (engine._admit_handoffs flips it to WAITING).
                continue
            if (len(self.running) + admitting
                    >= self.config.max_num_seqs):
                break
            if (max_tokens is not None
                    and tokens_planned >= max_tokens):
                break
            if seq.num_computed_tokens == 0 and not seq.pages:
                # First touch: reuse cached prefix pages, then allocate
                # the remainder for the whole prompt up front.
                matched = self.cache.match_prefix(
                    seq.prompt_token_ids, seq.cache_salt)
                if self.restore_hook is not None:
                    restored = self.restore_hook(
                        seq.prompt_token_ids, matched,
                        seq.cache_salt,
                    )
                    if restored and self.tracer is not None:
                        self.tracer.event(
                            seq.seq_id, "offload_restore",
                            pages=len(restored))
                    matched = matched + restored
                if (self.sp_threshold is not None
                        and not matched
                        and seq.num_prompt_tokens >= self.sp_threshold):
                    # Long cold prompt: context-parallel whole-prompt
                    # prefill, one sequence per dispatch. Runs alone —
                    # if chunked work was already gathered this step,
                    # emit that first and pick the long prompt up next
                    # step.
                    if chunks:
                        break
                    try:
                        seq.pages = list(self.cache.allocate_pages(
                            self._pages_needed(
                                seq, seq.num_prompt_tokens)))
                    except OutOfPagesError:
                        seq.pages = []
                        if not self.running:
                            logger.error(
                                "Request %s can never fit in the KV "
                                "cache; aborting", seq.seq_id)
                            self.waiting.remove(seq)
                            self._finish(seq, FinishReason.ABORT)
                            self.newly_aborted.append(seq)
                            continue
                        logger.warning(
                            "KV cache full: request %s waits",
                            seq.seq_id)
                        return None
                    if seq.first_scheduled_time is None:
                        seq.first_scheduled_time = time.time()
                    return PrefillPlan(chunks=[PrefillChunk(
                        seq=seq,
                        chunk_start=0,
                        chunk_tokens=list(seq.prompt_token_ids),
                        is_last_chunk=True,
                    )], sp=True)
                seq.pages = matched
                seq.num_hashed_pages = len(matched)
                seq.num_computed_tokens = len(matched) * self.page_size
                needed = self._pages_needed(seq, seq.num_prompt_tokens)
                try:
                    seq.pages.extend(self.cache.allocate_pages(needed))
                except OutOfPagesError:
                    self.cache.free_sequence(seq.pages)
                    seq.pages = []
                    seq.num_computed_tokens = 0
                    if chunks:
                        break  # run what we already gathered
                    if not self.running:
                        # Nothing will ever free pages: permanent.
                        logger.error(
                            "Request %s can never fit in the KV cache; "
                            "aborting", seq.seq_id
                        )
                        self.waiting.remove(seq)
                        self._finish(seq, FinishReason.ABORT)
                        self.newly_aborted.append(seq)
                        continue
                    logger.warning(
                        "KV cache full: request %s waits", seq.seq_id
                    )
                    return None
            start = seq.num_computed_tokens
            end = min(start + self.config.prefill_chunk_size,
                      seq.num_prompt_tokens)
            if max_tokens is not None:
                end = min(end, start + (max_tokens - tokens_planned))
            is_last = end == seq.num_prompt_tokens
            if seq.first_scheduled_time is None:
                seq.first_scheduled_time = time.time()
            chunks.append(PrefillChunk(
                seq=seq,
                chunk_start=start,
                chunk_tokens=seq.prompt_token_ids[start:end],
                is_last_chunk=is_last,
            ))
            tokens_planned += end - start
            if is_last:
                admitting += 1
        if not chunks:
            return None
        return PrefillPlan(chunks=chunks)

    def _pages_needed(self, seq: Sequence, target_tokens: int) -> int:
        have = len(seq.pages) * self.page_size
        if target_tokens <= have:
            return 0
        return -(-(target_tokens - have) // self.page_size)

    def _ensure_decode_capacity(self, lookahead: int = 1,
                                per_seq: Optional[Dict[str, int]]
                                = None) -> None:
        """Every running sequence needs page slots for its next decode
        window: min(lookahead, its own remaining budget) tokens — a
        row near its budget reserves only what its burst can write.
        ``per_seq`` (speculative plans) overrides the uniform lookahead
        with a per-sequence one (1 + draft length)."""
        for seq in list(self.running):
            if seq.state != SequenceState.RUNNING:
                # Preempted earlier in this very pass (we iterate a
                # snapshot): allocating pages to a WAITING victim
                # would leak them when prefill re-allocates from
                # scratch.
                continue
            ahead = (per_seq.get(seq.seq_id, 1) if per_seq is not None
                     else lookahead)
            ahead = max(1, min(ahead, self._seq_budget(seq)))
            needed = self._pages_needed(seq, seq.total_len + ahead)
            if needed == 0:
                continue
            try:
                seq.pages.extend(self.cache.allocate_pages(needed))
            except OutOfPagesError:
                # Preempt the lowest-priority, newest running sequence
                # (docs/qos.md): max over (priority, arrival) — the
                # exact inverse of the admission sort, and never a
                # sequence more important than the one needing pages
                # (seq itself is in the candidate set).
                victim = max(self.running,
                             key=lambda s: (s.priority, s.arrival_time))
                self._preempt(victim)
                if victim is seq:
                    continue
                try:
                    seq.pages.extend(self.cache.allocate_pages(needed))
                except OutOfPagesError:
                    self._preempt(seq)

    def _preempt(self, seq: Sequence) -> None:
        self._log_preemption(seq)
        self.num_preemptions += 1
        if self.tracer is not None:
            self.tracer.event(seq.seq_id, "preempt",
                              generated=len(seq.output_token_ids))
        self.running.remove(seq)
        # Preempt-to-offload (docs/qos.md): ship the victim's committed
        # KV pages to the offload tier BEFORE freeing them — the cache
        # fires evict_listener lazily on slot reuse, far too late for a
        # deterministic restore. 0 pages / no hook / hook failure all
        # degrade to the classic drop-and-recompute.
        evicted = 0
        if self.evict_hook is not None:
            try:
                evicted = self.evict_hook(seq)
            except Exception:
                logger.exception(
                    "Preempt-to-offload failed for %s; falling back to "
                    "recompute", seq.seq_id)
                evicted = 0
        outcome = "offloaded" if evicted else "recompute"
        self.preempt_offload_outcomes[outcome] = (
            self.preempt_offload_outcomes.get(outcome, 0) + 1)
        if evicted and self.tracer is not None:
            self.tracer.event(seq.seq_id, "preempt_offload",
                              pages=evicted)
        self.cache.free_sequence(seq.pages)
        seq.pages = []
        seq.num_hashed_pages = 0
        # Recompute everything including generated tokens as "prompt".
        # num_prior_output_tokens keeps every generated-so-far budget
        # (max_tokens, min_tokens, seeded emitted index) counting
        # across the fold; presence/frequency penalty counts restart
        # (the folded tokens move to the repetition-penalty prompt
        # mask instead — a documented approximation under preemption).
        seq.num_prior_output_tokens += len(seq.output_token_ids)
        seq.prompt_token_ids = seq.all_token_ids
        seq.output_token_ids = []
        seq.num_computed_tokens = 0
        if evicted:
            # Park like a disagg handoff (docs/disaggregation.md): the
            # engine re-admits via _admit_handoffs once the shipped
            # pages are reachable (immediately for the host tier), and
            # the ordinary first-touch restore path pulls them back —
            # miss/unreachable degrades to recompute via the same
            # tri-state the handoff path already handles.
            seq.transition(SequenceState.AWAITING_KV)
            seq.handoff_arrival_time = time.time()
            if self.tracer is not None:
                self.tracer.event(seq.seq_id, "awaiting_kv_park",
                                  pages=evicted)
        else:
            seq.transition(SequenceState.WAITING)
        self.waiting.appendleft(seq)

    def _log_preemption(self, seq: Sequence) -> None:
        now = time.monotonic()
        if now - self._preempt_log_ts < _PREEMPT_LOG_INTERVAL_S:
            self._preempt_log_suppressed += 1
            return
        if self._preempt_log_suppressed:
            logger.warning(
                "Preempting %s (KV cache pressure; %d preemptions "
                "suppressed in the last %.0fs)", seq.seq_id,
                self._preempt_log_suppressed, _PREEMPT_LOG_INTERVAL_S)
        else:
            logger.warning("Preempting %s (KV cache pressure)",
                           seq.seq_id)
        self._preempt_log_ts = now
        self._preempt_log_suppressed = 0

    # ---- completion callbacks (driven by the engine) ----------------------

    def on_prefill_executed(self, chunk: PrefillChunk,
                            sampled_token: Optional[int]) -> None:
        seq = chunk.seq
        if seq.state in (SequenceState.ABORTED, SequenceState.FINISHED):
            return  # aborted while the chunk was in flight on device
        seq.num_computed_tokens = (chunk.chunk_start
                                   + len(chunk.chunk_tokens))
        if self.tracer is not None:
            self.tracer.event(
                seq.seq_id, "prefill_chunk",
                start=chunk.chunk_start,
                tokens=len(chunk.chunk_tokens),
                last=chunk.is_last_chunk)
        self.cache.commit_full_pages(
            seq.prompt_token_ids[:seq.num_computed_tokens],
            seq.pages, seq.num_hashed_pages, seq.cache_salt,
        )
        seq.num_hashed_pages = min(
            len(seq.pages),
            seq.num_computed_tokens // self.page_size,
        )
        if chunk.is_last_chunk:
            assert sampled_token is not None
            try:
                self.waiting.remove(seq)
            except ValueError:
                return  # raced with an abort that already dequeued it
            seq.transition(SequenceState.RUNNING)
            seq.first_token_time = time.time()
            if self.tracer is not None:
                self.tracer.event(seq.seq_id, "first_token",
                                  token=int(sampled_token))
            self.running.append(seq)
            self._append_token(seq, sampled_token)

    def finish_handoff(self, seq: Sequence) -> None:
        """Disagg prefill handoff complete (the engine already shipped
        the committed KV to the offload tier): retire the sequence so
        its pages free immediately for the next prefill burst."""
        if seq in self.running:
            self.running.remove(seq)
        self._finish(seq, FinishReason.HANDOFF)

    def on_spec_executed(self, seq: Sequence) -> None:
        """Post-verify accounting rollback (docs/speculative.md).

        The verify pass computed KV through ``total_len_before +
        draft_len`` positions, but only the accepted prefix + bonus
        were appended; the committed-token count must reflect exactly
        the kept tokens — the rejected tail's KV is junk past
        ``total_len``, causally invisible and overwritten by the next
        step. Never counting it is the state rollback."""
        if seq.state == SequenceState.RUNNING:
            seq.num_computed_tokens = seq.total_len

    def append_decode_token(self, seq: Sequence, token: int) -> bool:
        """Append one decoded token; returns False if the sequence is
        no longer running (remaining window tokens are discarded)."""
        if seq.state != SequenceState.RUNNING:
            return False
        self._append_token(seq, token)
        return seq.state == SequenceState.RUNNING

    def _append_token(self, seq: Sequence, token: int) -> None:
        seq.output_token_ids.append(token)
        if self.guided_advance is not None and seq.fsm_state is not None:
            self.guided_advance(seq, token)
        stop_ids = seq.sampling.stop_token_ids
        # min_tokens: the device suppresses stop ids while under the
        # minimum (model_runner._suppress_payload), but only up to
        # STOP_SET_WIDTH of them — a wider set's overflow could still
        # be sampled, and must not end the sequence early.
        past_min = seq.num_generated > seq.sampling.min_tokens
        if (not seq.sampling.ignore_eos and token in stop_ids
                and past_min):
            self._finish(seq, FinishReason.STOP)
            self.running.remove(seq)
        elif seq.num_generated >= seq.sampling.max_tokens:
            self._finish(seq, FinishReason.LENGTH)
            self.running.remove(seq)
        elif seq.total_len >= self.config.max_model_len:
            self._finish(seq, FinishReason.LENGTH)
            self.running.remove(seq)

    def _finish(self, seq: Sequence, reason: FinishReason) -> None:
        if seq.state in (SequenceState.FINISHED, SequenceState.ABORTED):
            return
        seq.transition(SequenceState.ABORTED if reason == FinishReason.ABORT
                       else SequenceState.FINISHED)
        seq.finish_reason = reason
        seq.finish_time = time.time()
        if self.proposer is not None:
            self.proposer.drop(seq.seq_id)
        if seq.pages:
            self.cache.free_sequence(seq.pages)
            seq.pages = []
