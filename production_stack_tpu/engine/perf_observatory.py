"""Device-level performance observatory (docs/observability.md).

Four ledgers the serving layer was previously blind to, all host-side
and allocation-free on the committed-token path:

- **compile ledger** — every jitted step program is wrapped in
  :class:`InstrumentedJit`; a growth of the executable cache between
  two calls is a compile event, recorded with its kind, wall time and
  the ``(rows, W)`` shape key that triggered it. A recompile storm
  shows up on the dashboard within one scrape instead of only in a
  slow test.
- **HBM memory ledger** — an always-available analytic breakdown of
  device bytes from the engine config (weights from the actual param
  tree, KV pages + int8 scale tensors from the page math, step
  buffers), plus ``device.memory_stats()`` where the backend supports
  it. The int8 capacity-expansion math (docs/kv_quantization.md) is a
  live gauge here instead of a config-time log line.
- **step-time / MFU ledger** — per-kind device-wait seconds and
  useful tokens processed, turned into an analytic model-FLOPs
  utilization figure against a per-device peak-FLOPs table (or the
  ``--device-peak-flops`` override). Unknown devices report MFU 0
  rather than a guessed peak.
- **dispatch timing fold-in** — the PSTPU_TIMING wall clocks that
  previously only went to the log also accumulate here, so
  ``GET /debug/compiles`` carries per-kind dispatch statistics.

Everything is plain-Python counter arithmetic on the single step
thread: no device transfers, no jax imports at call time, and every
hook is behind an ``observatory is None`` guard so the byte-identical
greedy parity tests can pin zero overhead.
"""

from __future__ import annotations

import collections
import statistics
import time
from typing import Any, Deque, Dict, List, Optional, Tuple

# Peak bf16 matmul FLOP/s per chip for the MFU estimate (same table
# as bench.py's _PEAK_FLOPS). Prefix-matched against
# ``device.device_kind``; an unknown device (including CPU) resolves
# to 0.0 so the MFU gauge reads 0 instead of lying.
PEAK_FLOPS_BY_DEVICE_KIND = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def resolve_peak_flops(device_kind: Optional[str],
                       override: float = 0.0) -> float:
    """Per-chip peak FLOP/s: explicit override wins, then the device
    table, then 0.0 (honest "unknown")."""
    if override and override > 0:
        return float(override)
    if device_kind:
        lowered = device_kind.lower()
        for k, v in PEAK_FLOPS_BY_DEVICE_KIND.items():
            if lowered.startswith(k.lower()):
                return v
    return 0.0


class PerfObservatory:
    """Host-side device-performance ledgers for one model runner.

    Single-writer by construction (the engine step thread); readers
    (the /metrics handler, debug endpoints) only see monotone counter
    snapshots, so no locking is needed.
    """

    def __init__(self, config, *, param_count: int = 0,
                 params_bytes: int = 0,
                 device_kind: Optional[str] = None,
                 compile_ring_size: int = 128):
        self.config = config
        self.param_count = int(param_count)
        self.params_bytes = int(params_bytes)
        self.device_kind = device_kind or ""
        self.peak_flops = resolve_peak_flops(
            self.device_kind,
            float(getattr(config, "device_peak_flops", 0.0) or 0.0))
        # Dense decoder forward pass: ~2 FLOPs per parameter per token.
        self.flops_per_token = 2.0 * self.param_count

        # ---- compile ledger ------------------------------------------
        self._compile_events: Dict[str, int] = {}
        self._compile_seconds: Dict[str, float] = {}
        self._cache_sizes: Dict[str, int] = {}
        self._jits: Dict[str, Any] = {}
        self._compile_ring: Deque[Dict[str, Any]] = collections.deque(
            maxlen=compile_ring_size)

        # ---- step / MFU ledger ---------------------------------------
        self._device_seconds: Dict[str, float] = {}
        self._tokens: Dict[str, int] = {}
        self.device_seconds_total = 0.0
        self.tokens_total = 0
        # Bounded per-kind ring of recent step durations; its medians
        # feed vllm:engine_step_time_median_seconds{kind} and the
        # router-side drift sentinel (obs/drift.py).
        self._step_durations: Dict[str, Deque[float]] = {}
        self._step_ring_size = 512

        # ---- dispatch-timing fold-in (PSTPU_TIMING walls) ------------
        self._dispatch_count: Dict[str, int] = {}
        self._dispatch_seconds: Dict[str, float] = {}

        # ---- attention-impl info ledger ------------------------------
        self._attention_impls: Dict[str, str] = {}

    # ---- compile ledger --------------------------------------------------

    def register_jit(self, kind: str, fn: Any) -> None:
        """Zero-init a program kind at wrap time so the gauges exist
        (at 0) before the first dispatch, and keep the jit handle for
        live executable-cache-size reads."""
        self._compile_events.setdefault(kind, 0)
        self._compile_seconds.setdefault(kind, 0.0)
        self._cache_sizes.setdefault(kind, 0)
        self._jits[kind] = fn

    def on_compile(self, kind: str,
                   key: Optional[Tuple[int, ...]],
                   seconds: float, cache_size: int) -> None:
        self._compile_events[kind] = self._compile_events.get(kind, 0) + 1
        self._compile_seconds[kind] = (
            self._compile_seconds.get(kind, 0.0) + float(seconds))
        self._cache_sizes[kind] = int(cache_size)
        self._compile_ring.append({
            "kind": kind,
            "key": list(key) if key is not None else None,
            "seconds": round(float(seconds), 6),
            "cache_size": int(cache_size),
            "ts": time.time(),
        })

    def compile_events_by_kind(self) -> Dict[str, int]:
        return dict(self._compile_events)

    def compile_seconds_by_kind(self) -> Dict[str, float]:
        return dict(self._compile_seconds)

    def compile_events_total(self, kind: Optional[str] = None) -> int:
        if kind is not None:
            return self._compile_events.get(kind, 0)
        return sum(self._compile_events.values())

    def executable_cache_sizes(self) -> Dict[str, int]:
        """Live per-kind executable-cache sizes, read from the jit
        handles where the runtime exposes ``_cache_size`` and falling
        back to the last compile-time observation otherwise."""
        sizes: Dict[str, int] = {}
        for kind, tracked in self._cache_sizes.items():
            fn = self._jits.get(kind)
            size_fn = getattr(fn, "_cache_size", None)
            if callable(size_fn):
                try:
                    sizes[kind] = int(size_fn())
                    continue
                except Exception:
                    pass
            sizes[kind] = tracked
        return sizes

    def recent_compiles(self, limit: int = 32) -> List[Dict[str, Any]]:
        items = list(self._compile_ring)
        if limit >= 0:
            items = items[-limit:]
        return items

    # ---- dispatch timing -------------------------------------------------

    def on_timing(self, kind: str, wall: float) -> None:
        self._dispatch_count[kind] = self._dispatch_count.get(kind, 0) + 1
        self._dispatch_seconds[kind] = (
            self._dispatch_seconds.get(kind, 0.0) + float(wall))

    def dispatch_timings(self) -> Dict[str, Dict[str, float]]:
        return {kind: {"count": self._dispatch_count[kind],
                       "wall_seconds": round(
                           self._dispatch_seconds.get(kind, 0.0), 6)}
                for kind in sorted(self._dispatch_count)}

    def compile_report(self, limit: int = 32) -> Dict[str, Any]:
        return {
            "events": self.compile_events_by_kind(),
            "seconds": {k: round(v, 6)
                        for k, v in self._compile_seconds.items()},
            "executable_cache_sizes": self.executable_cache_sizes(),
            "recent": self.recent_compiles(limit),
            "timings": self.dispatch_timings(),
        }

    # ---- HBM memory ledger -----------------------------------------------

    def hbm_bytes(self) -> Dict[str, int]:
        """Analytic device-byte breakdown. ``kv_pages`` + ``kv_scales``
        equals ``num_pages * page_size * kv_bytes_per_token`` exactly
        (the post-expansion int8 budget), and ``weights`` is the exact
        leaf-sum of the sharded param tree."""
        model = self.config.model
        cache = self.config.cache
        sched = self.config.scheduler
        slots = 2 * model.num_hidden_layers * model.num_key_value_heads
        tokens = cache.num_pages * cache.page_size
        if cache.resolved_kv_dtype() == "int8":
            kv_pages = slots * tokens * model.head_dim  # int8 data
            kv_scales = slots * tokens * 4  # f32 per-slot scales
        else:
            import jax.numpy as jnp
            itemsize = jnp.dtype(model.jax_dtype).itemsize
            kv_pages = slots * tokens * model.head_dim * itemsize
            kv_scales = 0
        rows = sched.max_num_seqs + sched.prefill_batch_size
        width = sched.prefill_chunk_size
        # Step-buffer estimate: one f32 logits plane plus the i32
        # token/descriptor blocks for the widest mixed batch.
        step_buffers = rows * model.vocab_size * 4 + rows * width * 4
        return {
            "weights": int(self.params_bytes),
            "kv_pages": int(kv_pages),
            "kv_scales": int(kv_scales),
            "step_buffers": int(step_buffers),
        }

    def memory_report(self) -> Dict[str, Any]:
        analytic = self.hbm_bytes()
        report: Dict[str, Any] = {
            "analytic": analytic,
            "total_analytic_bytes": sum(analytic.values()),
            "kv_cache_dtype": self.config.cache.resolved_kv_dtype(),
            "num_pages": self.config.cache.num_pages,
            "page_size": self.config.cache.page_size,
            "param_count": self.param_count,
        }
        try:  # backend-dependent; absent on CPU
            import jax
            stats = jax.devices()[0].memory_stats()
            if stats:
                report["device"] = {
                    k: int(v) for k, v in stats.items()
                    if isinstance(v, (int, float))}
        except Exception:
            pass
        return report

    # ---- step-time / MFU ledger ------------------------------------------

    def on_step(self, kind: str, device_s: float, tokens: int) -> None:
        self._device_seconds[kind] = (
            self._device_seconds.get(kind, 0.0) + float(device_s))
        self._tokens[kind] = self._tokens.get(kind, 0) + int(tokens)
        self.device_seconds_total += float(device_s)
        self.tokens_total += int(tokens)
        ring = self._step_durations.get(kind)
        if ring is None:
            ring = self._step_durations[kind] = collections.deque(
                maxlen=self._step_ring_size)
        ring.append(float(device_s))

    def device_seconds_by_kind(self) -> Dict[str, float]:
        return dict(self._device_seconds)

    def step_time_medians(self) -> Dict[str, float]:
        """Median recent step duration per kind (seconds). Computed
        over the bounded ring, so it tracks the *current* regime
        rather than the lifetime mean the cumulative counters give."""
        out: Dict[str, float] = {}
        for kind, ring in self._step_durations.items():
            if ring:
                out[kind] = statistics.median(ring)
        return out

    def tokens_by_kind(self) -> Dict[str, int]:
        return dict(self._tokens)

    def mfu(self) -> float:
        """Useful-token MFU: committed/processed tokens (prefill chunk
        tokens + emitted decode tokens) against the peak — rejected
        speculative drafts and pad rows count as lost utilization,
        which is the operationally interesting number. 0.0 when the
        device peak is unknown."""
        if (self.peak_flops <= 0 or self.device_seconds_total <= 0
                or self.tokens_total <= 0):
            return 0.0
        achieved = self.flops_per_token * self.tokens_total
        return achieved / self.device_seconds_total / self.peak_flops

    # ---- attention-impl info ledger --------------------------------------

    def set_attention_impl(self, phase: str, impl: str) -> None:
        self._attention_impls[phase] = impl

    def attention_impls(self) -> Dict[str, str]:
        return dict(self._attention_impls)


class InstrumentedJit:
    """Transparent wrapper around one jitted step program.

    Detects compile events as growth of the executable cache between
    two calls (compilation is synchronous inside ``__call__`` even
    under async dispatch, so the wall-clock delta on a growing call is
    trace+compile time). The owner's ``observatory`` attribute is
    looked up at call time: set it to ``None`` and every call is a
    plain passthrough — the parity tests pin that path.

    ``_cache_size`` and attribute access forward to the wrapped jit so
    existing introspection (bench warmup, tests) keeps working.
    """

    def __init__(self, kind: str, fn: Any, owner: Any):
        self.kind = kind
        self.fn = fn
        self._owner = owner
        obs = getattr(owner, "observatory", None)
        if obs is not None:
            obs.register_jit(kind, fn)

    def __call__(self, *args, **kwargs):
        obs = getattr(self._owner, "observatory", None)
        size_fn = getattr(self.fn, "_cache_size", None)
        if obs is None or size_fn is None:
            return self.fn(*args, **kwargs)
        before = size_fn()
        t0 = time.perf_counter()
        out = self.fn(*args, **kwargs)
        after = size_fn()
        if after != before:
            key: Optional[Tuple[int, ...]] = None
            # args[3] is the tokens block for every step program —
            # its (rows, W) shape is the bucket key that compiled.
            if len(args) > 3 and hasattr(args[3], "shape"):
                key = tuple(int(d) for d in args[3].shape)
            obs.on_compile(self.kind, key,
                           time.perf_counter() - t0, after)
        return out

    def _cache_size(self) -> int:
        size_fn = getattr(self.fn, "_cache_size", None)
        return int(size_fn()) if callable(size_fn) else 0

    def __getattr__(self, name):
        return getattr(self.fn, name)
