"""Tokenizer abstraction.

HF tokenizers load from a local path (this environment has no network
egress; in production the Helm chart mounts the model PVC, reference
deployment-vllm-multi.yaml:110-115 HF_HOME). The ByteTokenizer is a
dependency-free fallback used by tests/benchmarks with tiny models.
"""

from __future__ import annotations

from typing import List, Optional


class BaseTokenizer:
    eos_token_id: int

    def encode(self, text: str) -> List[int]:
        raise NotImplementedError

    def decode(self, token_ids: List[int]) -> str:
        raise NotImplementedError

    @property
    def vocab_size(self) -> int:
        raise NotImplementedError


class ByteTokenizer(BaseTokenizer):
    """UTF-8 bytes + <bos>=256, <eos>=257. Vocab 512 (room for specials)."""

    BOS = 256
    EOS = 257

    def __init__(self):
        self.eos_token_id = self.EOS

    def encode(self, text: str) -> List[int]:
        return [self.BOS] + list(text.encode("utf-8"))

    def decode(self, token_ids: List[int]) -> str:
        data = bytes(t for t in token_ids if 0 <= t < 256)
        return data.decode("utf-8", errors="replace")

    @property
    def vocab_size(self) -> int:
        return 512


class BenchTokenizer(ByteTokenizer):
    """ByteTokenizer whose decode covers a full random-weights vocab.

    A --random-weights bench server pairs a real model vocab (e.g.
    32,128) with the dependency-free byte tokenizer (decode range
    0-255) — greedy tokens under random weights are almost surely
    >= 256, which ByteTokenizer.decode silently drops, so a streaming
    client sees only empty content deltas: no TTFT signal and
    gen_tokens == 0 (observed in the round-5 engine QPS sweep,
    benchmarks/results/round5_notes.md). Here every id >= 258 decodes
    to one printable ASCII char, so each generated token yields
    exactly one non-empty delta — what a latency benchmark needs —
    while encode stays byte-level (realistic prompt token counts).
    """

    def __init__(self, vocab_size: int = 32128):
        # The paired model's vocab (bench-1b default) — ByteTokenizer's
        # inherited 512 would make any vocab-sized consumer (logit-bias
        # masks, prompt validation) treat most servable ids as OOV.
        super().__init__()
        self._vocab_size = vocab_size

    @property
    def vocab_size(self) -> int:
        return self._vocab_size

    def decode(self, token_ids: List[int]) -> str:
        out: List[str] = []
        run: List[int] = []  # contiguous byte-range ids
        for t in token_ids:
            if 0 <= t < 256:
                run.append(t)
                continue
            if run:
                out.append(bytes(run).decode("utf-8", errors="replace"))
                run = []
            if t >= 258:  # 256/257 are bos/eos (specials: skipped)
                out.append(chr(33 + (t - 258) % 94))
        if run:
            out.append(bytes(run).decode("utf-8", errors="replace"))
        return "".join(out)


class HFTokenizer(BaseTokenizer):
    def __init__(self, path: str):
        from transformers import AutoTokenizer
        self._tok = AutoTokenizer.from_pretrained(path)
        self.eos_token_id = self._tok.eos_token_id

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text)

    def encode_rendered(self, text: str) -> List[int]:
        """Encode text a chat template already rendered: no extra
        special tokens (the template embeds BOS etc. itself)."""
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, token_ids: List[int]) -> str:
        return self._tok.decode(token_ids, skip_special_tokens=True)

    def apply_chat_template(self, messages) -> Optional[List[int]]:
        try:
            return self._tok.apply_chat_template(
                messages, add_generation_prompt=True
            )
        except Exception:
            return None

    @property
    def vocab_size(self) -> int:
        return len(self._tok)


def get_tokenizer(spec: Optional[str]) -> BaseTokenizer:
    """spec: None/'byte' -> ByteTokenizer; 'bench' -> BenchTokenizer
    (full-vocab decode for random-weights servers); otherwise a local
    HF path."""
    if spec in (None, "byte"):
        return ByteTokenizer()
    if spec == "bench":
        return BenchTokenizer()
    return HFTokenizer(spec)


def render_chat_prompt(tokenizer: BaseTokenizer, messages,
                       chat_template: Optional[str] = None) -> List[int]:
    """Messages -> prompt token ids.

    Priority: explicit ``chat_template`` (Jinja source, the --chat-template
    override the reference chart renders into vllm serve,
    deployment-vllm-multi.yaml:99-103) > the model's own template >
    a simple role-tagged rendering.
    """
    if chat_template:
        try:
            import jinja2
            text = jinja2.Template(chat_template).render(
                messages=messages, add_generation_prompt=True
            )
            # The template renders its own special tokens; encoding
            # must not prepend a second BOS.
            if isinstance(tokenizer, HFTokenizer):
                return tokenizer.encode_rendered(text)
            return tokenizer.encode(text)
        except Exception as e:
            # Fall back to the model/default template — but loudly: a
            # silently ignored operator override serves wrong prompts.
            from production_stack_tpu.utils.log import init_logger
            init_logger(__name__).warning(
                "--chat-template failed to render (%r); falling back "
                "to the model's own template", e)
    if isinstance(tokenizer, HFTokenizer):
        ids = tokenizer.apply_chat_template(messages)
        if ids is not None:
            return ids
    text = "".join(
        f"<|{m.get('role', 'user')}|>\n{m.get('content', '')}\n"
        for m in messages
    ) + "<|assistant|>\n"
    return tokenizer.encode(text)
