"""Engine configuration objects.

These play the role of the ``vllm serve`` flags the reference's Helm chart
renders (reference helm/templates/deployment-vllm-multi.yaml:57-103:
--max-model-len, --dtype, --tensor-parallel-size, --enable-chunked-prefill,
--enable-prefix-caching), re-expressed for a JAX engine.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax.numpy as jnp

from production_stack_tpu.qos import parse_priority

_DTYPE_MAP = {
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float16": jnp.float16,
}


@dataclasses.dataclass
class ModelConfig:
    """Architecture hyperparameters (HF-config compatible field names)."""

    name: str = "tiny-llama"
    architecture: str = "llama"  # llama | opt | gpt2 | mistral | qwen2
    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 5632
    num_hidden_layers: int = 22
    num_attention_heads: int = 32
    num_key_value_heads: int = 4
    head_dim: Optional[int] = None
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    dtype: str = "bfloat16"
    # OPT/GPT-2 specifics
    do_layer_norm_before: bool = True
    activation: str = "silu"  # silu (llama) | relu (opt) | gelu (gpt2)
    # Qwen2-style q/k/v projection biases on the llama-family body.
    attention_bias: bool = False
    # Mixtral-style sparse MoE (architecture == "mixtral").
    num_local_experts: int = 0
    num_experts_per_tok: int = 2
    # Weight-only quantization: none | int8 (engine/quantization.py).
    quantization: str = "none"
    # Decode attention implementation:
    #   auto            -> pallas on TPU, xla elsewhere (resolved by the
    #                      model runner at init)
    #   xla             -> gather-based reference (ops/attention.py)
    #   pallas          -> Pallas kernel (ops/paged_attention_pallas.py)
    #   pallas-interpret-> Pallas interpreter mode (CPU testing)
    attention_impl: str = "auto"
    # Per-shape overrides resolved by the model runner's compile probe:
    # decode and prefill kernels degrade to XLA *independently* (a
    # Mosaic failure in one must not discard the other — round-2
    # lesson, VERDICT §weak 3). None = follow attention_impl.
    attention_impl_decode: Optional[str] = None
    attention_impl_prefill: Optional[str] = None
    # Unified-step ([R, W] mixed batch) kernel, resolved separately: the
    # fused ragged kernel (pallas_ragged) needs both a lowering probe
    # AND a measured microbench win before auto serves it; None =
    # compose the family prefill impl (model_runner._resolve_unified_impl).
    attention_impl_unified: Optional[str] = None

    def __post_init__(self):
        if self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads

    @property
    def jax_dtype(self):
        return _DTYPE_MAP[self.dtype]

    @classmethod
    def from_hf_config(cls, hf: dict, name: str = "") -> "ModelConfig":
        """Build from a HuggingFace config.json dict."""
        arch = (hf.get("architectures") or ["LlamaForCausalLM"])[0].lower()
        if "gpt2" in arch:
            return cls(
                name=name or hf.get("_name_or_path", "gpt2"),
                architecture="gpt2",
                vocab_size=hf["vocab_size"],
                hidden_size=hf["n_embd"],
                intermediate_size=hf.get("n_inner") or 4 * hf["n_embd"],
                num_hidden_layers=hf["n_layer"],
                num_attention_heads=hf["n_head"],
                num_key_value_heads=hf["n_head"],
                max_position_embeddings=hf["n_positions"],
                tie_word_embeddings=True,
                activation="gelu",
                dtype="bfloat16",
            )
        if "mixtral" in arch:
            return cls(
                name=name or hf.get("_name_or_path", "mixtral"),
                architecture="mixtral",
                vocab_size=hf["vocab_size"],
                hidden_size=hf["hidden_size"],
                intermediate_size=hf["intermediate_size"],
                num_hidden_layers=hf["num_hidden_layers"],
                num_attention_heads=hf["num_attention_heads"],
                num_key_value_heads=hf.get(
                    "num_key_value_heads", hf["num_attention_heads"]),
                head_dim=hf.get("head_dim"),
                max_position_embeddings=hf.get(
                    "max_position_embeddings", 4096),
                rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
                rope_theta=hf.get("rope_theta", 1e6),
                tie_word_embeddings=hf.get("tie_word_embeddings",
                                           False),
                num_local_experts=hf.get("num_local_experts", 8),
                num_experts_per_tok=hf.get("num_experts_per_tok", 2),
                activation="silu",
                dtype="bfloat16",
            )
        if "opt" in arch:
            return cls(
                name=name or hf.get("_name_or_path", "opt"),
                architecture="opt",
                vocab_size=hf["vocab_size"],
                hidden_size=hf["hidden_size"],
                intermediate_size=hf.get("ffn_dim", 4 * hf["hidden_size"]),
                num_hidden_layers=hf["num_hidden_layers"],
                num_attention_heads=hf["num_attention_heads"],
                num_key_value_heads=hf["num_attention_heads"],
                max_position_embeddings=hf["max_position_embeddings"],
                tie_word_embeddings=hf.get("tie_word_embeddings", True),
                do_layer_norm_before=hf.get("do_layer_norm_before", True),
                activation="relu",
                dtype="bfloat16",
            )
        qwen = "qwen2" in arch
        return cls(
            name=name or hf.get("_name_or_path", "llama"),
            architecture="qwen2" if qwen else "llama",
            # Qwen2 puts biases on q/k/v (HF Qwen2Attention); plain
            # Llama exposes the same switch via attention_bias.
            attention_bias=(True if qwen
                            else hf.get("attention_bias", False)),
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            intermediate_size=hf["intermediate_size"],
            num_hidden_layers=hf["num_hidden_layers"],
            num_attention_heads=hf["num_attention_heads"],
            num_key_value_heads=hf.get(
                "num_key_value_heads", hf["num_attention_heads"]
            ),
            head_dim=hf.get("head_dim"),
            max_position_embeddings=hf.get("max_position_embeddings", 4096),
            rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
            rope_theta=hf.get("rope_theta", 10000.0),
            tie_word_embeddings=hf.get("tie_word_embeddings", False),
            activation="silu",
            dtype="bfloat16",
        )


@dataclasses.dataclass
class CacheConfig:
    """Paged KV cache geometry."""

    page_size: int = 16  # tokens per page
    num_pages: int = 1024  # total pages in HBM (per shard)
    enable_prefix_caching: bool = True
    # HBM buffer layout (models/llama.py cached_attention):
    #   auto      -> per_layer, except pipeline/context-parallel
    #                configs (which shard or walk the stacked L axis)
    #                resolve to stacked. Decided on-chip 2026-07-31
    #                (benchmarks/results/decode_probe.json: per_layer
    #                13.5 vs stacked 27.4 ms/token-step; engine bench
    #                11.07 vs 5.94 req/s).
    #   stacked   -> one [L, kv, pages, d, page_size] array per k/v;
    #                layer writes are in-place scatters at a static
    #                layer index.
    #   per_layer -> a tuple of L [kv, pages, d, page_size] buffers;
    #                every scatter/kernel touches exactly one layer's
    #                buffer (67 MB vs 2.1 GB operands at the 1B bench
    #                config) and donation aliases buffers 1:1.
    cache_layout: str = "auto"
    # KV page storage dtype (docs/kv_quantization.md):
    #   auto / bf16 -> pages in the model compute dtype (bf16 in
    #                  serving; an f32 model keeps f32 pages) — the
    #                  two spellings are synonyms so --kv-cache-dtype
    #                  bf16 states the default explicitly.
    #   int8        -> pages quantized on write (symmetric per-slot
    #                  scales, ops/quant_kv.py) and dequantized
    #                  in-kernel; the page budget is expanded to spend
    #                  the SAME HBM bytes (~2x pages at bf16 widths).
    kv_cache_dtype: str = "auto"

    def max_tokens(self) -> int:
        return self.page_size * self.num_pages

    def resolved_kv_dtype(self) -> str:
        """'int8' or 'bf16' (the full-precision family; the actual
        page dtype is the model compute dtype)."""
        return "int8" if self.kv_cache_dtype == "int8" else "bf16"

    def kv_slot_bytes(self, model: "ModelConfig") -> int:
        """HBM bytes one cached token costs per kv head per k-or-v
        plane: head_dim values plus, for int8, one f32 scale."""
        if self.resolved_kv_dtype() == "int8":
            return model.head_dim + 4
        return model.head_dim * jnp.dtype(model.jax_dtype).itemsize

    def kv_bytes_per_token(self, model: "ModelConfig") -> int:
        """Total KV bytes appended per committed token (k and v,
        all layers, all kv heads)."""
        return (2 * model.num_hidden_layers
                * model.num_key_value_heads
                * self.kv_slot_bytes(model))


@dataclasses.dataclass
class SchedulerConfig:
    """Continuous-batching shape budget (all static under jit)."""

    max_num_seqs: int = 8  # decode batch width (padded)
    max_model_len: int = 2048
    prefill_chunk_size: int = 512  # chunked prefill unit
    # Distinct sequences whose next chunks batch into one prefill
    # program (fixed row count; rows pad with the trash page).
    prefill_batch_size: int = 4
    # Decode iterations fused into one compiled program (tokens feed
    # back on device; 1 host round-trip per K tokens). 1 = off.
    decode_steps: int = 1
    # Deferred KV writes inside a decode burst: append each step's K/V
    # to a dense [B, S, kv, d] tail (one-hot select, no scatter) and
    # flush the tail to the pages ONCE per burst per layer. Motivated
    # by the round-5 on-chip ablation (results/round5_notes.md): the
    # per-step paged scatters cost ~5.1 of 11.1 ms for ~1 MB written.
    # Llama-family single-runner path only (guarded in model_runner);
    # requires decode_steps > 1.
    deferred_kv_writes: bool = False
    # Draft-free speculative decoding (prompt lookup, docs/
    # speculative.md): propose up to K continuation tokens per row
    # from each sequence's own n-gram history and verify all K+1
    # positions in ONE fixed-shape forward pass. 0 = off. Composes
    # with decode_steps > 1 as a hybrid — steps where the proposer
    # drafted run the verify program, draft-less steps fall back to
    # the multi-step decode burst. Incompatible with
    # deferred_kv_writes (the verify step must write draft KV
    # eagerly so later draft positions attend to earlier ones).
    speculative_k: int = 0
    # Minimum n-gram length the proposer must match in the sequence's
    # history before drafting its continuation.
    speculative_min_match: int = 2
    # Overlapped async execution pipeline (docs/async_pipeline.md):
    # plan and dispatch decode step N+1 — feeding step N's sampled
    # tokens forward as a device array — before step N's results are
    # read back to the host, so completion work (detokenize, stop
    # checks, stream fan-out) overlaps device execution. Composes
    # with speculative_k (the ahead plan assumes one committed token
    # and reconciles extra accepted tokens through the stale-token
    # drop path) and with decode_steps > 1 (burst windows execute
    # synchronously between pipelined single-step stretches).
    # Greedy output is byte-identical to the synchronous loop.
    async_scheduling: bool = False
    # Unified ragged step (docs/unified_step.md): plan prefill chunks
    # INTO decode/spec steps under a token budget instead of
    # alternating phases, executing genuinely mixed batches through
    # one fixed-shape [rows, W] ragged program (span-gather +
    # spec_verify emit 1..k+1 tokens per row through one shape).
    # Pure-decode and pure-prefill steps keep the bimodal dispatch
    # paths, so greedy streams stay byte-identical when no mixing
    # happens. The server's --unified-step auto resolves this on for
    # eligible single-runner configs (unified_step_eligible).
    unified_step: bool = False
    max_queue_len: int = 1024

    def max_pages_per_seq(self, page_size: int) -> int:
        return math.ceil(self.max_model_len / page_size)


@dataclasses.dataclass
class ParallelConfig:
    """Device-mesh shape; tensor parallel maps to the 'tp' mesh axis over
    ICI (reference passes --tensor-parallel-size to vLLM + /dev/shm for
    NCCL, deployment-vllm-multi.yaml:84-87,226-233 — XLA needs neither)."""

    tensor_parallel_size: int = 1
    data_parallel_size: int = 1
    # Layer stages over the 'pp' mesh axis — a SERVING feature here
    # (parallel/pipeline_serving.py), unlike the reference which has no
    # pipeline parallelism at all (SURVEY.md §2.6).
    pipeline_parallel_size: int = 1
    # Sequence/context parallelism over the 'sp' mesh axis: prompts at
    # least ``long_prefill_threshold`` tokens prefill in ONE dispatch
    # with the sequence sharded T/sp per device and ring attention
    # doing the O(T^2) mixing (parallel/context_serving.py) — the
    # long-context strategy the reference lacks entirely.
    context_parallel_size: int = 1
    # Prompts this long (tokens) take the sp prefill path; defaults to
    # 2 x prefill_chunk_size when context_parallel_size > 1.
    long_prefill_threshold: Optional[int] = None
    # Forced ICI-slice count for topology discovery
    # (parallel/topology.py): 0 = auto-discover (TPU slice coords,
    # process grouping). >0 splits the visible devices into that many
    # equal contiguous slices — how the XLA_FLAGS-forced CPU harness
    # rehearses multi-slice layouts in CI.
    num_slices: int = 0
    # Per-axis placement overrides for the MeshPlan, as
    # "axis=ici|any" pairs ("tp=ici,pp=any"). 'auto' keeps the
    # defaults: tp/sp confined to one ICI domain (a replica is a
    # slice), dp/pp free to cross slices over DCN.
    mesh_placement: str = "auto"

    def __post_init__(self):
        if self.num_slices < 0:
            raise ValueError("parallel.num_slices must be >= 0")
        # Reject placement typos at config time, not first dispatch.
        from production_stack_tpu.parallel.topology import (
            parse_placement,
        )
        parse_placement(self.mesh_placement)


@dataclasses.dataclass
class LoRAConfig:
    """Multi-LoRA serving (the reference's --enable-lora pass-through,
    helm/templates/deployment-vllm-multi.yaml:66-68; see engine/lora.py)."""

    enable: bool = False
    max_loras: int = 8  # adapter slots (slot 0 is always the base model)
    max_lora_rank: int = 16


@dataclasses.dataclass
class OffloadConfig:
    """KV offload tiers (the LMCache analogue; see engine/offload.py)."""

    enable: bool = False
    host_pool_bytes: int = 2 * 1024 ** 3
    remote_url: Optional[str] = None


@dataclasses.dataclass
class KVEconConfig:
    """Cluster KV economy knobs (docs/kv_economy.md).

    Engine-side semantics: the summary tracker behind GET /kv/summary
    and the host pool's eviction hysteresis. The cluster cache server
    reuses the same flag spellings for its authoritative server-side
    policy (admission by distinct-requester demand, TTL + watermark
    chain eviction) with its own defaults — see
    engine/cache_server.py.
    """

    # Hot chains advertised in the /kv/summary snapshot (and tracker
    # sizing: up to 8x this many chains are tracked pre-admission).
    summary_top_k: int = 64
    # Decayed hit count a chain needs before it is advertised as hot.
    admit_hits: int = 2
    # Seconds an idle chain stays in the summary tracker (0 = no TTL).
    ttl_s: float = 900.0
    # Host offload pool fill fractions: above high, evict down to low
    # (oldest-first, same order as the pool's LRU). 1.0/1.0 keeps the
    # legacy evict-exactly-at-capacity behavior.
    watermark_high: float = 1.0
    watermark_low: float = 1.0

    def __post_init__(self):
        if self.summary_top_k < 1:
            raise ValueError("kvecon.summary_top_k must be >= 1")
        if self.admit_hits < 1:
            raise ValueError("kvecon.admit_hits must be >= 1")
        if self.ttl_s < 0:
            raise ValueError("kvecon.ttl_s must be >= 0")
        if not 0.0 < self.watermark_low <= self.watermark_high <= 1.0:
            raise ValueError(
                "kvecon watermarks must satisfy 0 < low <= high <= 1 "
                f"(got low={self.watermark_low!r} "
                f"high={self.watermark_high!r})")


@dataclasses.dataclass
class QoSConfig:
    """Overload quality-of-service (docs/qos.md): priority classes,
    preempt-to-offload, and engine-side shedding."""

    # Priority class assumed for requests without an x-priority
    # header: interactive | batch | background. Defaults to the
    # middle class so unlabeled traffic stays sheddable.
    default_priority: str = "batch"
    # Under page pressure, ship the preemption victim's committed KV
    # pages to the offload tier (when one is configured) instead of
    # discarding them, so re-admission restores pages instead of
    # recomputing the whole prompt. Inert without --enable-kv-offload.
    preempt_to_offload: bool = True
    # Waiting-queue fill fraction (of max_queue_len) past which the
    # server sheds non-interactive submissions with 429 + Retry-After
    # instead of letting them age out in the queue.
    shed_threshold: float = 0.95

    def __post_init__(self):
        # Raises ValueError on anything outside the priority
        # vocabulary — the config-contract's tested rejection for
        # invalid priority strings.
        parse_priority(self.default_priority)
        if not 0.0 < self.shed_threshold <= 1.0:
            raise ValueError(
                "qos.shed_threshold must be in (0, 1] "
                f"(got {self.shed_threshold!r})")


@dataclasses.dataclass
class AutotuneConfig:
    """Self-tuning controller policy (docs/autotuning.md).

    Shared cadence/guardrail knobs plus the per-controller clamp
    bands the autotuner enforces. The mode gate is the contract:
    ``off`` never even constructs controllers' tick path, ``shadow``
    computes and span-logs decisions without applying them (the A/B
    story), ``on`` closes the loop.
    """

    # off | shadow | on (autotune.MODES).
    mode: str = "off"
    # Seconds between controller ticks (the bounded cadence).
    interval_s: float = 2.0
    # Relative dead-band: proposals within this fraction of the
    # current knob value are dropped (hysteresis against jitter).
    dead_band: float = 0.05
    # Comma-separated controller-name allowlist, or "all".
    controllers: str = "all"
    # Guardrail blame window: a perf-drift flip / 5m-burn rise
    # freezes every controller that applied a decision this recently.
    freeze_window_s: float = 30.0
    # 5m SLO burn rate at/above which a rise trips the guardrail.
    burn_threshold: float = 1.0
    # Decode ITL p99 target the prefill-budget controller steers
    # toward (grow mixed-step admission while under, shrink over).
    target_itl_ms: float = 50.0
    # Clamp floors/caps for individual controllers. Spec-k cap is
    # --speculative-k itself; checkpoint interval floors/caps bound
    # the halving/doubling walk; shed floor keeps QoS from shedding
    # more than operators signed up for.
    min_spec_k: int = 1
    min_checkpoint_interval_tokens: int = 64
    max_checkpoint_interval_tokens: int = 4096
    min_shed_threshold: float = 0.5

    def __post_init__(self):
        if self.mode not in ("off", "shadow", "on"):
            raise ValueError(
                "autotune.mode must be 'off', 'shadow' or 'on' "
                f"(got {self.mode!r})")
        if self.interval_s <= 0:
            raise ValueError("autotune.interval_s must be > 0")
        if not 0.0 <= self.dead_band < 1.0:
            raise ValueError(
                "autotune.dead_band must be in [0, 1) "
                f"(got {self.dead_band!r})")
        if self.freeze_window_s <= 0:
            raise ValueError("autotune.freeze_window_s must be > 0")
        if self.min_spec_k < 1:
            raise ValueError("autotune.min_spec_k must be >= 1")
        if not 0.0 < self.min_shed_threshold <= 1.0:
            raise ValueError(
                "autotune.min_shed_threshold must be in (0, 1] "
                f"(got {self.min_shed_threshold!r})")
        if (self.min_checkpoint_interval_tokens < 1
                or self.max_checkpoint_interval_tokens
                < self.min_checkpoint_interval_tokens):
            raise ValueError(
                "autotune checkpoint interval bounds must satisfy "
                "1 <= min <= max (got "
                f"min={self.min_checkpoint_interval_tokens!r} "
                f"max={self.max_checkpoint_interval_tokens!r})")


@dataclasses.dataclass
class EngineConfig:
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    cache: CacheConfig = dataclasses.field(default_factory=CacheConfig)
    scheduler: SchedulerConfig = dataclasses.field(
        default_factory=SchedulerConfig)
    parallel: ParallelConfig = dataclasses.field(
        default_factory=ParallelConfig)
    offload: OffloadConfig = dataclasses.field(
        default_factory=OffloadConfig)
    lora: LoRAConfig = dataclasses.field(default_factory=LoRAConfig)
    qos: QoSConfig = dataclasses.field(default_factory=QoSConfig)
    kvecon: KVEconConfig = dataclasses.field(
        default_factory=KVEconConfig)
    autotune: AutotuneConfig = dataclasses.field(
        default_factory=AutotuneConfig)
    seed: int = 0
    # Disaggregated serving role (docs/disaggregation.md):
    #   both    -> monolithic engine (default; fully backward
    #              compatible): serves prefill + decode.
    #   prefill -> computes prompt KV, ships committed pages over the
    #              offload wire and answers with a handoff descriptor
    #              instead of a token stream (POST /v1/disagg/prefill).
    #   decode  -> accepts handoff submissions (POST
    #              /v1/disagg/handoff), restores the shipped pages and
    #              streams decode from the first sampled token.
    engine_role: str = "both"
    # Seconds a decode-role engine holds a handoff in AWAITING_KV while
    # its pages are unreachable (remote tier down) before degrading to
    # a full prompt recompute. 0 = recompute immediately on a miss.
    handoff_timeout_s: float = 30.0
    # Per-chip peak FLOP/s for the observatory's MFU gauge
    # (engine/perf_observatory.py). 0 = resolve from the device-kind
    # table; unknown devices (including CPU) then report MFU 0 rather
    # than a guessed utilization.
    device_peak_flops: float = 0.0
    # Mid-stream crash safety (docs/crash_recovery.md): every N
    # generated tokens, ship a streaming sequence's committed KV pages
    # to the offload tier and publish a resume descriptor so the
    # router can re-submit the stream to another engine after this
    # process dies. 0 = no checkpointing (streams die with the
    # engine). Inert without an offload tier for the page ship, but
    # the descriptor (token journal) is still published so a resume
    # can recompute.
    checkpoint_interval_tokens: int = 0
    # Seconds a single engine step may run before /health flips to
    # 503 so the router's prober rotates the replica out (a hung
    # device program blocks the step thread; the asyncio health
    # handler keeps serving). 0 = watchdog disabled.
    step_watchdog_s: float = 0.0

    def __post_init__(self):
        if self.engine_role not in ("prefill", "decode", "both"):
            raise ValueError(
                "engine_role must be 'prefill', 'decode' or 'both' "
                f"(got {self.engine_role!r})")
        if self.handoff_timeout_s < 0:
            raise ValueError("handoff_timeout_s must be >= 0")
        if self.device_peak_flops < 0:
            raise ValueError("device_peak_flops must be >= 0")
        if self.checkpoint_interval_tokens < 0:
            raise ValueError("checkpoint_interval_tokens must be >= 0")
        if self.step_watchdog_s < 0:
            raise ValueError("step_watchdog_s must be >= 0")
        if self.engine_role == "prefill":
            # A prefill-role engine never decodes past the first
            # sampled token, so decode-side machinery is dead weight
            # at best and a config lie at worst — reject it loudly.
            if self.scheduler.speculative_k > 0:
                raise ValueError(
                    "engine_role='prefill' is incompatible with "
                    "speculative_k > 0 (speculation accelerates "
                    "decode; a prefill-role engine hands off after "
                    "the first token; docs/disaggregation.md "
                    "§interactions)")
            # async_scheduling on a prefill-role engine is legal but
            # inert: prefill dispatches run synchronously, so the
            # pipeline simply never goes ahead. The server's
            # --async-scheduling auto still resolves it off for the
            # role (no decode steps to overlap).
        if self.cache.kv_cache_dtype not in ("auto", "bf16", "int8"):
            raise ValueError(
                "cache.kv_cache_dtype must be 'auto', 'bf16' or "
                f"'int8' (got {self.cache.kv_cache_dtype!r})")
        if self.cache.resolved_kv_dtype() == "int8":
            # int8 now composes with pipeline/context parallelism:
            # the pp/sp shard_map boundaries carry QuantKV pytree
            # specs (congruent data+scale sharding, mirroring
            # shard_cache) — the former exclusivity raises dissolved
            # with the topology-aware mesh (docs/parallelism.md).
            # Spend the SAME HBM byte budget on more (narrower)
            # pages: a full-precision slot is head_dim * itemsize
            # bytes, an int8 slot head_dim + 4 (f32 scale) — ~1.9x
            # more pages at bf16 widths. Guarded by a sentinel on the
            # CacheConfig object because dataclasses.replace(self)
            # re-runs __post_init__ on the SAME CacheConfig instance.
            if not getattr(self.cache, "_kv_pages_expanded", False):
                full_slot = (self.model.head_dim
                             * jnp.dtype(self.model.jax_dtype).itemsize)
                expanded = (self.cache.num_pages * full_slot
                            // (self.model.head_dim + 4))
                self.cache = dataclasses.replace(
                    self.cache, num_pages=max(expanded,
                                              self.cache.num_pages))
                self.cache._kv_pages_expanded = True
        if self.scheduler.speculative_k > 0:
            if self.scheduler.deferred_kv_writes:
                raise ValueError(
                    "speculative_k is incompatible with "
                    "deferred_kv_writes (the verify step writes draft "
                    "KV eagerly so accepted tokens can attend to it; "
                    "docs/speculative.md §interactions)")
            if self.scheduler.speculative_min_match < 1:
                raise ValueError("speculative_min_match must be >= 1")
        # async_scheduling now composes with decode_steps > 1 (burst
        # windows run synchronously between pipelined single-step
        # stretches) and speculative_k > 0 (the ahead plan assumes
        # one committed token per row and reconciles multi-accept
        # steps through the stale-token drop path) — the former
        # exclusivity raises died with the unified ragged step
        # (docs/unified_step.md §dissolved-rules).
        # Learned-position-embedding models (gpt2/opt) index a fixed
        # [max_positions, h] table; JAX clamps out-of-range gathers
        # silently, so positions past the table would all reuse the
        # last row and quietly degrade long generations. Cap the
        # serving length at the model's limit instead.
        if (self.model.architecture in ("gpt2", "opt")
                and self.scheduler.max_model_len
                > self.model.max_position_embeddings):
            from production_stack_tpu.utils.log import init_logger
            init_logger(__name__).warning(
                "max_model_len %d exceeds %s's position table (%d); "
                "clamping to %d",
                self.scheduler.max_model_len, self.model.architecture,
                self.model.max_position_embeddings,
                self.model.max_position_embeddings,
            )
            self.scheduler = dataclasses.replace(
                self.scheduler,
                max_model_len=self.model.max_position_embeddings,
            )


# ---- staticcheck config-contract markers -------------------------------
# Read statically by staticcheck/analyzers/config_contract.py (keep
# them literals). Every field reachable from EngineConfig must map to
# a tpu-engine CLI flag by naming convention, appear in
# CLI_FLAG_ALIASES, or be declared INTERNAL here — so "operators
# can't reach this knob" is always a decision, never an accident.

CLI_FLAG_ALIASES = {
    # field path                    flag that sets it
    "model.name": "--model",
    "cache.enable_prefix_caching": "--disable-prefix-caching",
    "lora.enable": "--enable-lora",
    "offload.enable": "--enable-kv-offload",
    "offload.host_pool_bytes": "--kv-host-pool-bytes",
    "offload.remote_url": "--kv-remote-url",
    "kvecon.summary_top_k": "--kv-summary-top-k",
    "kvecon.admit_hits": "--kv-admit-hits",
    "kvecon.ttl_s": "--kv-ttl-s",
    "kvecon.watermark_high": "--kv-watermark-high",
    "kvecon.watermark_low": "--kv-watermark-low",
    "autotune.mode": "--autotune",
    "autotune.interval_s": "--autotune-interval-s",
    "autotune.dead_band": "--autotune-dead-band",
    "autotune.controllers": "--autotune-controllers",
    "autotune.freeze_window_s": "--autotune-freeze-window-s",
    "autotune.burn_threshold": "--autotune-burn-threshold",
    "autotune.target_itl_ms": "--autotune-target-itl-ms",
    "autotune.min_spec_k": "--autotune-min-spec-k",
    "autotune.min_checkpoint_interval_tokens":
        "--autotune-min-checkpoint-interval-tokens",
    "autotune.max_checkpoint_interval_tokens":
        "--autotune-max-checkpoint-interval-tokens",
    "autotune.min_shed_threshold": "--autotune-min-shed-threshold",
}

INTERNAL_FIELDS = {
    # ModelConfig architecture hyperparameters are owned by the
    # checkpoint's HF config.json (from_hf_config) — a CLI override
    # would desync weights from geometry.
    "model.architecture",
    "model.vocab_size",
    "model.hidden_size",
    "model.intermediate_size",
    "model.num_hidden_layers",
    "model.num_attention_heads",
    "model.num_key_value_heads",
    "model.head_dim",
    "model.max_position_embeddings",
    "model.rms_norm_eps",
    "model.rope_theta",
    "model.tie_word_embeddings",
    "model.do_layer_norm_before",
    "model.activation",
    "model.attention_bias",
    "model.num_local_experts",
    "model.num_experts_per_tok",
    # Per-shape kernel overrides resolved by the model runner's
    # compile probe, not operator-set (--attention-impl is the knob).
    "model.attention_impl_decode",
    "model.attention_impl_prefill",
    "model.attention_impl_unified",
    # Data parallelism is derived mesh residue (devices not consumed
    # by tp/pp/sp), never requested directly.
    "parallel.data_parallel_size",
}

# Mutually-exclusive feature combos: (field_a, field_b, token). The
# analyzer requires a config-time `raise ValueError` in this module
# whose message contains `token`, AND a pytest.raises test under
# tests/ referencing both `token` and field_b's name — deleting
# either the rejection or its test is a staticcheck failure.
EXCLUSIVITY_RULES = (
    ("scheduler.speculative_k", "scheduler.deferred_kv_writes",
     "deferred_kv"),
    ("engine_role", "scheduler.speculative_k", "engine_role"),
)
# Dissolved by the unified ragged step (docs/unified_step.md):
#   async_scheduling x decode_steps, async_scheduling x
#   speculative_k, engine_role x async_scheduling. Those combos are
#   now legal compositions, not rejected pairs.
# Dissolved by the topology-aware mesh + pp/cp ragged step
#   (docs/parallelism.md): kv_cache_dtype x pipeline_parallel_size,
#   kv_cache_dtype x context_parallel_size — QuantKV pytree specs
#   flow through the pp/sp shard_map boundaries with congruent
#   data+scale sharding (parallel/mesh.py shard_cache).


def bench_1b_model_config() -> ModelConfig:
    """The 1B-class llama geometry the TPU bench serves (bench.py) and
    benchmarks/chip_sweep.sh's ``--model bench-1b`` server runs — one
    definition so the sweep drives exactly the benched config."""
    return ModelConfig(
        name="llama-1b-class",
        architecture="llama",
        vocab_size=32128,
        hidden_size=2048,
        intermediate_size=5632,
        num_hidden_layers=16,
        num_attention_heads=32,
        num_key_value_heads=8,
        head_dim=64,
        max_position_embeddings=2048,
        dtype="bfloat16",
    )


def tiny_model_config(architecture: str = "llama") -> ModelConfig:
    """A tiny model for tests/benchmarks that runs anywhere."""
    return ModelConfig(
        name=f"tiny-{architecture}",
        architecture=architecture,
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2 if architecture == "llama" else 4,
        max_position_embeddings=512,
        activation={"llama": "silu", "opt": "relu",
                    "gpt2": "gelu"}[architecture],
        dtype="float32",
    )
