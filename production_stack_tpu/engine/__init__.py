"""TPU-native serving engine.

The piece the reference outsources to vLLM (SURVEY.md §7 step 3): a
JAX/XLA engine with a paged KV cache, continuous batching under XLA's
static-shape constraint (bucketed prefill + fixed-width decode batch),
Pallas attention kernels, and an OpenAI-compatible HTTP front end whose
``/metrics`` exposition matches the names the router scrapes
(reference src/vllm_router/stats/engine_stats.py:46-55).
"""
