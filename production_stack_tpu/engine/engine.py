"""LLMEngine: ties scheduler + cache manager + model runner together.

Synchronous core (one ``step()`` = one compiled device program) with a
``generate()`` convenience for tests/benchmarks; the HTTP server
(engine/server.py) drives the same core from a background thread and
streams per-token outputs through asyncio queues.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Dict, List, Optional

from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.kv_cache import PagedCacheManager
from production_stack_tpu.engine.model_runner import ModelRunner
from production_stack_tpu.engine.scheduler import Scheduler
from production_stack_tpu.engine.sequence import (
    SamplingParams,
    Sequence,
    SequenceState,
)
from production_stack_tpu.engine.tokenizer import (
    BaseTokenizer,
    get_tokenizer,
)
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


@dataclass
class StepOutput:
    seq_id: str
    new_token: Optional[int]
    finished: bool
    finish_reason: Optional[str]
    # (sampled_logprob, [(token_id, logprob), ...]) when the request
    # asked for logprobs; None otherwise.
    logprobs: Optional[tuple] = None


class LLMEngine:
    def __init__(self, config: EngineConfig, mesh=None, params=None,
                 tokenizer: Optional[BaseTokenizer] = None):
        self.config = config
        self.tokenizer = tokenizer or get_tokenizer(None)
        self.cache_manager = PagedCacheManager(config.cache)
        sp_threshold = None
        if config.parallel.context_parallel_size > 1:
            sp_threshold = (config.parallel.long_prefill_threshold
                            or 2 * config.scheduler.prefill_chunk_size)
        # Guided JSON decoding (engine/guided.py): built EAGERLY for
        # byte-range tokenizers so multihost workers hold identical
        # tables before the first guided payload arrives (a lazy
        # host-0-only build would desync the step broadcast). HF
        # subword tokenizers: None — the server rejects
        # response_format json_object for them with a 400.
        self.guided_fsm = None
        from production_stack_tpu.engine.tokenizer import ByteTokenizer
        if isinstance(self.tokenizer, ByteTokenizer):
            from production_stack_tpu.engine.guided import build_json_fsm
            self.guided_fsm = build_json_fsm(self.tokenizer)
        self.scheduler = Scheduler(
            config.scheduler, config.cache, self.cache_manager,
            sp_threshold=sp_threshold,
            guided_advance=self._guided_advance,
        )
        self.runner = ModelRunner(config, mesh=mesh, params=params)
        if self.guided_fsm is not None:
            self.runner.set_guided_tables(self.guided_fsm)
        self.sequences: Dict[str, Sequence] = {}
        # QoS (docs/qos.md): priority class for requests that don't
        # carry an explicit one.
        from production_stack_tpu.qos import parse_priority
        self.default_priority = int(
            parse_priority(config.qos.default_priority))
        self._lock = threading.Lock()
        from production_stack_tpu.engine.metrics import EngineMetrics
        self.metrics = EngineMetrics()
        # Overlapped async pipeline state (docs/async_pipeline.md):
        # at most ONE dispatched-but-unread decode step. ``_idle_mark``
        # timestamps the moment the device drained its queue so the
        # next dispatch can account the idle gap — the quantity the
        # pipeline exists to shrink.
        self._in_flight = None
        self._idle_mark: Optional[float] = None
        # Row/spec detail for the step about to be accounted, staged
        # by the execute helpers for the flight recorder (tracer set
        # only); drained by _account_step.
        self._step_note: Optional[dict] = None
        # (kind, useful tokens) for the step about to be accounted,
        # staged by the execute helpers for the device performance
        # observatory's step/MFU ledger; drained by _account_step.
        # A cheap tuple, staged unconditionally (unlike _step_note,
        # which allocates a dict and is tracer-gated).
        self._obs_note: Optional[tuple] = None
        self.offload = None
        if config.offload.enable:
            self._init_offload()
        # Disaggregated serving (docs/disaggregation.md): descriptor
        # payloads for completed prefill handoffs (drained by the
        # server via take_handoff_info) and cumulative role counters.
        self._handoff_info: Dict[str, dict] = {}
        self.disagg_prefill_requests = 0
        self.disagg_decode_requests = 0
        self.disagg_kv_bytes_shipped = 0
        # Mid-stream crash safety (docs/crash_recovery.md): latest
        # resume descriptor per live streaming sequence (drained by
        # the server via take_checkpoint and relayed to the router as
        # an SSE comment frame), plus per-seq cadence/ship bookkeeping
        # and cumulative counters.
        self._checkpoints: Dict[str, dict] = {}
        self._ckpt_last_tokens: Dict[str, int] = {}
        self._ckpt_shipped_pages: Dict[str, int] = {}
        self.checkpoint_ships = 0
        self.checkpoint_kv_bytes = 0
        self.stream_resumes = 0
        # End-to-end tracing (docs/observability.md): the server
        # installs an engine/tracing.EngineTracer here; the library
        # default is None and every emission site is behind an
        # ``is None`` check, so untraced engines allocate no span
        # objects on the hot path.
        self._tracer = None

    @property
    def tracer(self):
        return self._tracer

    @tracer.setter
    def tracer(self, tracer) -> None:
        # Mirrored onto the scheduler so chunk/preempt/first-token
        # events emit without a back-reference to the engine.
        self._tracer = tracer
        self.scheduler.tracer = tracer

    def _init_offload(self) -> None:
        import numpy as np

        from production_stack_tpu.engine.offload import (
            HostKVPool,
            KVOffloadManager,
            RemoteKVClient,
        )
        # A per-process requester id: the managed cluster cache counts
        # DISTINCT requesters demanding a chain for admission
        # promotion (docs/kv_economy.md).
        remote = (RemoteKVClient(
                      self.config.offload.remote_url,
                      requester=f"engine-{uuid.uuid4().hex[:12]}")
                  if self.config.offload.remote_url else None)
        # Tier keys are namespaced by the actual page storage format
        # (int8 vs the model dtype) so pods with different
        # --kv-cache-dtype sharing a remote cache never alias.
        kv_dtype = ("int8" if self.runner.kv_quantized
                    else str(np.dtype(self.config.model.jax_dtype)))
        self.offload = KVOffloadManager(
            host_pool=HostKVPool(
                self.config.offload.host_pool_bytes,
                watermark_high=self.config.kvecon.watermark_high,
                watermark_low=self.config.kvecon.watermark_low),
            remote=remote,
            kv_dtype=kv_dtype,
        )
        self.cache_manager.evict_listener = self._on_page_evicted
        self.scheduler.restore_hook = self._restore_offloaded_prefix
        if self.config.qos.preempt_to_offload:
            # Preempt-to-offload (docs/qos.md): preemption victims
            # ship their committed pages over the same wire instead of
            # discarding them.
            self.scheduler.evict_hook = self._evict_sequence_kv
        logger.info("KV offload enabled (host pool %d MiB%s)",
                    self.config.offload.host_pool_bytes // 2 ** 20,
                    ", remote tier" if remote else "")

    def _on_page_evicted(self, page_id: int, page_hash) -> None:
        # 2 arrays for full-precision pages, 4 (data + scales) for
        # int8 pages; the tiers carry the tuple opaquely.
        payload = self.runner.read_page(page_id)
        self.offload.offload_page(page_hash, *payload)

    def _evict_sequence_kv(self, seq: Sequence) -> int:
        """Preempt-to-offload (docs/qos.md): ship the victim's
        committed KV pages to the offload tier before the scheduler
        frees them, returning the shipped page count.

        The restorable prefix is everything but the last token (the
        prefix-cache ``usable`` bound: the final token must reprefill
        to produce logits), and its KV is fully written — decode
        commits a token's KV one step after sampling it, so positions
        0..total_len-2 are always on device at a plan boundary. The
        generated-token pages are first committed to the hash table
        (prompt-time hashing stopped at the prompt), so the shipped
        chain and the first-touch restore chain are the same
        content-hash sequence — that identity is what makes the
        offload round trip byte-exact. The cache's lazy
        evict_listener cannot do this job: it fires on HBM slot
        reuse, long after the victim's pages were freed."""
        from production_stack_tpu.engine.kv_cache import (
            PagedCacheManager,
        )
        if self.offload is None or not seq.pages:
            return 0
        usable = seq.total_len - 1
        tokens = seq.all_token_ids[:usable]
        self.cache_manager.commit_full_pages(
            tokens, seq.pages, seq.num_hashed_pages, seq.cache_salt)
        hashes = PagedCacheManager.chain_hashes(
            tokens, self.cache_manager.page_size, seq.cache_salt)
        chain = self.offload.chain_id(hashes[0]) if hashes else None
        shipped = 0
        for page_id, page_hash in zip(seq.pages, hashes):
            payload = self.runner.read_page(page_id)
            self.offload.offload_page(page_hash, *payload, chain=chain)
            shipped += 1
        return shipped

    def _restore_offloaded_prefix(self, prompt_token_ids,
                                  matched_pages, cache_salt=0):
        """After an in-HBM prefix miss, pull further pages from the
        host/remote tiers into freshly allocated HBM pages."""
        from production_stack_tpu.engine.kv_cache import (
            OutOfPagesError,
            PagedCacheManager,
        )
        usable = len(prompt_token_ids) - 1
        hashes = PagedCacheManager.chain_hashes(
            prompt_token_ids[:usable], self.cache_manager.page_size,
            cache_salt,
        )
        remaining = hashes[len(matched_pages):]
        n = self.offload.lookup_chain(remaining)
        if n == 0:
            return []
        try:
            pages = self.cache_manager.allocate_pages(n)
        except OutOfPagesError:
            return []
        t0 = time.perf_counter()
        restored = []
        # One batched round trip for every remote miss in the chain
        # (POST /kv/batch_get) instead of N sequential GETs.
        payloads = self.offload.fetch_many(remaining[:n])
        for page_id, page_hash, payload in zip(
                pages, remaining[:n], payloads):
            expected_arity = 4 if self.runner.kv_quantized else 2
            if payload is None or len(payload) != expected_arity:
                # Tier raced an eviction, or a payload with the wrong
                # arity for this pod's page format: stop here (the
                # dtype-namespaced keys make the latter unreachable
                # short of tier corruption).
                self.cache_manager.free_sequence(
                    pages[len(restored):]
                )
                break
            self.runner.write_page(page_id, *payload)
            self.cache_manager.register_restored_page(
                page_id, page_hash
            )
            restored.append(page_id)
        self.offload.restored_pages += len(restored)
        if restored:
            self.cache_manager.prefix_hit_tokens += (
                len(restored) * self.cache_manager.page_size
            )
            # Restore latency (vllm:preempt_restore_latency_seconds):
            # the page-transfer cost that replaced a prompt recompute.
            self.metrics.on_preempt_restore(
                time.perf_counter() - t0)
        return restored

    # ---- request API ------------------------------------------------------

    def add_request(self, prompt_token_ids: List[int],
                    sampling: Optional[SamplingParams] = None,
                    seq_id: Optional[str] = None,
                    output_sink=None,
                    lora_name: Optional[str] = None,
                    handoff_prefill: bool = False,
                    request_id: Optional[str] = None,
                    priority: Optional[int] = None,
                    spec_off: bool = False) -> str:
        sampling = sampling or SamplingParams()
        stop_ids = list(sampling.stop_token_ids)
        if (not sampling.ignore_eos
                and self.tokenizer.eos_token_id is not None
                and self.tokenizer.eos_token_id not in stop_ids):
            stop_ids.append(self.tokenizer.eos_token_id)
        sampling.stop_token_ids = stop_ids
        fsm_state = None
        if sampling.guided is not None:
            if sampling.guided != "json":
                raise ValueError(
                    f"unsupported guided mode {sampling.guided!r} "
                    "(supported: 'json')")
            if self.guided_fsm is None:
                raise ValueError(
                    "guided JSON decoding requires a byte-range "
                    "tokenizer in this build (HF subword tokenizers "
                    "need an outlines-style vocabulary DFA product — "
                    "not yet supported)")
            fsm_state = 0
        lora_id = 0
        if lora_name is not None:
            if self.runner.lora_registry is None:
                raise ValueError("LoRA is not enabled on this engine")
            lora_id = self.runner.lora_registry.slot_for(lora_name)
        seq = Sequence(
            seq_id=seq_id or f"seq-{uuid.uuid4().hex[:16]}",
            prompt_token_ids=list(prompt_token_ids),
            sampling=sampling,
            output_sink=output_sink,
            lora_id=lora_id,
            cache_salt=(self.runner.lora_registry.cache_root(lora_id)
                        if lora_id else 0),
            fsm_state=fsm_state,
            handoff_prefill=handoff_prefill,
            request_id=request_id,
            priority=(self.default_priority if priority is None
                      else int(priority)),
            spec_off=spec_off,
        )
        with self._lock:
            if (not handoff_prefill
                    and self._cold_start_target(seq) is not None):
                # Shared cluster cache (docs/kv_economy.md): another
                # engine may already hold this prompt's prefix KV.
                # Park in AWAITING_KV so the step loop probes the
                # shared tier (one HEAD) before prefill — hit means a
                # batched restore instead of recompute, miss or tier
                # down degrades straight to compute.
                seq.transition(SequenceState.AWAITING_KV)
                seq.cold_start_probe = True
                seq.handoff_arrival_time = time.time()
            self.sequences[seq.seq_id] = seq
            try:
                self.scheduler.add_sequence(seq)
            except Exception:
                self.sequences.pop(seq.seq_id, None)
                raise
            if self._tracer is not None:
                self._tracer.start(
                    seq.seq_id, request_id=request_id,
                    prompt_tokens=seq.num_prompt_tokens)
                if seq.cold_start_probe:
                    self._tracer.event(seq.seq_id, "awaiting_kv_park")
        return seq.seq_id

    def _cold_start_target(self, seq: Sequence):
        """First full usable prompt page neither in HBM nor hashed
        locally — the page whose presence in the shared cluster cache
        decides whether a cold prompt restores or computes. None when
        there is no shared tier, prefix caching is off, or the local
        cache already covers the prompt (then the normal first-touch
        path handles everything). Caller holds self._lock."""
        from production_stack_tpu.engine.kv_cache import (
            PagedCacheManager,
        )
        if (self.offload is None or self.offload.remote is None
                or not self.config.cache.enable_prefix_caching):
            return None
        usable = len(seq.prompt_token_ids) - 1
        hashes = PagedCacheManager.chain_hashes(
            seq.prompt_token_ids[:usable],
            self.cache_manager.page_size, seq.cache_salt)
        for page_hash in hashes:
            if page_hash not in self.cache_manager._hash_to_page:
                return page_hash
        return None

    def add_handoff(self, prompt_token_ids: List[int],
                    first_token: int,
                    sampling: Optional[SamplingParams] = None,
                    seq_id: Optional[str] = None,
                    output_sink=None,
                    request_id: Optional[str] = None) -> str:
        """Accept a disaggregated prefill->decode handoff
        (docs/disaggregation.md): park the sequence in AWAITING_KV
        until its shipped pages are reachable in an offload tier
        (or the handoff timeout degrades it to recompute).

        The prefill engine's first sampled token is folded into the
        prompt exactly like scheduler._preempt folds generated tokens,
        with ``num_prior_output_tokens = 1`` keeping every budget
        honest; the caller (server handler) emits that first token to
        the client itself — this engine streams from token two.
        """
        sampling = sampling or SamplingParams()
        stop_ids = list(sampling.stop_token_ids)
        if (not sampling.ignore_eos
                and self.tokenizer.eos_token_id is not None
                and self.tokenizer.eos_token_id not in stop_ids):
            stop_ids.append(self.tokenizer.eos_token_id)
        sampling.stop_token_ids = stop_ids
        if sampling.guided is not None:
            raise ValueError(
                "guided decoding is not supported across a disagg "
                "handoff (automaton state does not transfer)")
        orig_max_tokens = sampling.max_tokens
        seq = Sequence(
            seq_id=seq_id or f"seq-{uuid.uuid4().hex[:16]}",
            prompt_token_ids=(list(prompt_token_ids)
                              + [int(first_token)]),
            sampling=sampling,
            output_sink=output_sink,
            state=SequenceState.AWAITING_KV,
            num_prior_output_tokens=1,
            handoff_arrival_time=time.time(),
            request_id=request_id,
        )
        with self._lock:
            self.sequences[seq.seq_id] = seq
            try:
                self.scheduler.add_sequence(seq)
            except Exception:
                self.sequences.pop(seq.seq_id, None)
                raise
            if self._tracer is not None:
                self._tracer.start(
                    seq.seq_id, request_id=request_id,
                    prompt_tokens=seq.num_prompt_tokens)
                self._tracer.event(seq.seq_id, "awaiting_kv_park")
            # Undo the admission clamp: it counts the folded first
            # token as prompt, which would end generation one token
            # earlier than the monolithic path. num_prior_output_tokens
            # plus the max_model_len finish check already bound this
            # sequence exactly as a monolithic engine would.
            sampling.max_tokens = orig_max_tokens
            self.disagg_decode_requests += 1
            if self.offload is None:
                # No tier to restore from: degrade to recompute now.
                seq.transition(SequenceState.WAITING)
                self.metrics.on_handoff_admitted(0.0)
                if self._tracer is not None:
                    self._tracer.event(
                        seq.seq_id, "awaiting_kv_restore",
                        waited_ms=0.0, outcome="no_tier")
        return seq.seq_id

    def add_resume(self, token_ids: List[int],
                   num_prior_output_tokens: int,
                   sampling: Optional[SamplingParams] = None,
                   seq_id: Optional[str] = None,
                   output_sink=None,
                   request_id: Optional[str] = None) -> str:
        """Resume a stream whose engine died mid-generation
        (docs/crash_recovery.md): ``token_ids`` is the journaled
        committed context (original prompt + every generated token up
        to the last checkpoint), folded into the prompt exactly like
        ``scheduler._preempt`` folds generated tokens, with
        ``num_prior_output_tokens`` keeping every budget honest. The
        sequence parks in ``AWAITING_KV``; the tri-state probe then
        restores the checkpointed pages from the offload tier — or
        degrades to a full recompute from the journal on a miss.
        Either way generation continues byte-identically for greedy
        sampling; nothing is replayed to the client (the server skips
        already-delivered text)."""
        sampling = sampling or SamplingParams()
        stop_ids = list(sampling.stop_token_ids)
        if (not sampling.ignore_eos
                and self.tokenizer.eos_token_id is not None
                and self.tokenizer.eos_token_id not in stop_ids):
            stop_ids.append(self.tokenizer.eos_token_id)
        sampling.stop_token_ids = stop_ids
        if sampling.guided is not None:
            raise ValueError(
                "guided decoding is not supported across a resume "
                "(automaton state does not transfer)")
        orig_max_tokens = sampling.max_tokens
        seq = Sequence(
            seq_id=seq_id or f"seq-{uuid.uuid4().hex[:16]}",
            prompt_token_ids=[int(t) for t in token_ids],
            sampling=sampling,
            output_sink=output_sink,
            state=SequenceState.AWAITING_KV,
            num_prior_output_tokens=int(num_prior_output_tokens),
            handoff_arrival_time=time.time(),
            request_id=request_id,
        )
        with self._lock:
            self.sequences[seq.seq_id] = seq
            try:
                self.scheduler.add_sequence(seq)
            except Exception:
                self.sequences.pop(seq.seq_id, None)
                raise
            if self._tracer is not None:
                self._tracer.start(
                    seq.seq_id, request_id=request_id,
                    prompt_tokens=seq.num_prompt_tokens)
                self._tracer.event(
                    seq.seq_id, "resume_restore",
                    prior_tokens=int(num_prior_output_tokens))
                self._tracer.event(seq.seq_id, "awaiting_kv_park")
            # Undo the admission clamp (see add_handoff): the folded
            # prior output would otherwise shrink the token budget.
            sampling.max_tokens = orig_max_tokens
            self.stream_resumes += 1
            if self.offload is None:
                # No tier to restore from: recompute from the journal.
                seq.transition(SequenceState.WAITING)
                self.metrics.on_handoff_admitted(0.0)
                if self._tracer is not None:
                    self._tracer.event(
                        seq.seq_id, "awaiting_kv_restore",
                        waited_ms=0.0, outcome="no_tier")
        return seq.seq_id

    def take_checkpoint(self, seq_id: str) -> Optional[dict]:
        """Drain the latest unsent resume descriptor for ``seq_id``
        (None when no new checkpoint landed since the last take)."""
        with self._lock:
            return self._checkpoints.pop(seq_id, None)

    def _checkpoint_tick(self) -> None:
        """Mid-stream crash safety (docs/crash_recovery.md): every
        ``config.checkpoint_interval_tokens`` generated tokens, ship a
        running stream's committed KV pages to the offload tier over
        the preempt-to-offload wire (incrementally — only pages not
        yet shipped) and stage a resume descriptor journaling the full
        committed token context. Skips guided and LoRA sequences
        (automaton state / adapter identity don't transfer). Without
        an offload tier the journal alone is staged, so a resume still
        recomputes rather than dying with this process."""
        from production_stack_tpu.engine.kv_cache import (
            PagedCacheManager,
        )
        interval = self.config.checkpoint_interval_tokens
        with self._lock:
            for seq in list(self.scheduler.running):
                if (seq.state != SequenceState.RUNNING
                        or seq.sampling.guided is not None
                        or seq.lora_id != 0):
                    continue
                last = self._ckpt_last_tokens.get(seq.seq_id, 0)
                if seq.num_generated - last < interval:
                    continue
                self._ckpt_last_tokens[seq.seq_id] = seq.num_generated
                # Committed restorable prefix: everything but the last
                # token (same bound as _evict_sequence_kv — the final
                # token's KV lands one step later and must reprefill).
                usable = seq.total_len - 1
                tokens = seq.all_token_ids[:usable]
                shipped = kv_bytes = 0
                if self.offload is not None and seq.pages:
                    self.cache_manager.commit_full_pages(
                        tokens, seq.pages, seq.num_hashed_pages,
                        seq.cache_salt)
                    hashes = PagedCacheManager.chain_hashes(
                        tokens, self.cache_manager.page_size,
                        seq.cache_salt)
                    done = self._ckpt_shipped_pages.get(seq.seq_id, 0)
                    pairs = list(zip(seq.pages, hashes))
                    chain = (self.offload.chain_id(hashes[0])
                             if hashes else None)
                    for page_id, page_hash in pairs[done:]:
                        payload = self.runner.read_page(page_id)
                        self.offload.offload_page(page_hash, *payload,
                                                  chain=chain)
                        kv_bytes += sum(int(a.nbytes) for a in payload)
                        shipped += 1
                    self._ckpt_shipped_pages[seq.seq_id] = len(pairs)
                self.checkpoint_ships += 1
                self.checkpoint_kv_bytes += kv_bytes
                self._checkpoints[seq.seq_id] = {
                    "tokens": [int(t) for t in seq.all_token_ids],
                    "prompt_tokens": seq.total_len - seq.num_generated,
                    "output_tokens": seq.num_generated,
                    "num_pages": self._ckpt_shipped_pages.get(
                        seq.seq_id, 0),
                    "kv_bytes": kv_bytes,
                }
                if self._tracer is not None:
                    self._tracer.event(
                        seq.seq_id, "checkpoint_ship",
                        pages=shipped, kv_bytes=kv_bytes,
                        tokens=seq.num_generated)

    def _drop_checkpoint_state(self, seq_id: str) -> None:
        """Caller holds self._lock (or the seq is already retired)."""
        self._checkpoints.pop(seq_id, None)
        self._ckpt_last_tokens.pop(seq_id, None)
        self._ckpt_shipped_pages.pop(seq_id, None)

    def take_handoff_info(self, seq_id: str) -> Optional[dict]:
        """Drain the descriptor payload recorded when ``seq_id``
        finished its prefill handoff (None if it never shipped)."""
        with self._lock:
            return self._handoff_info.pop(seq_id, None)

    def _ship_handoff(self, seq: Sequence) -> None:
        """Prefill-role completion: push the sequence's committed
        full-page KV to the offload tiers (push-on-prefill-done),
        record the descriptor payload for the server, and retire the
        sequence so its pages free for the next prefill burst. Caller
        holds self._lock."""
        from production_stack_tpu.engine.kv_cache import (
            PagedCacheManager,
        )
        info = {"num_pages": 0, "kv_bytes": 0, "page_keys": []}
        if self.offload is not None:
            hashes = PagedCacheManager.chain_hashes(
                seq.prompt_token_ids, self.cache_manager.page_size,
                seq.cache_salt)
            chain = (self.offload.chain_id(hashes[0])
                     if hashes else None)
            for page_id, page_hash in zip(seq.pages, hashes):
                payload = self.runner.read_page(page_id)
                self.offload.offload_page(page_hash, *payload,
                                          chain=chain)
                info["kv_bytes"] += sum(
                    int(a.nbytes) for a in payload)
                info["page_keys"].append(
                    self.offload.key_for(page_hash))
            info["num_pages"] = len(info["page_keys"])
        self._handoff_info[seq.seq_id] = info
        self.disagg_prefill_requests += 1
        self.disagg_kv_bytes_shipped += info["kv_bytes"]
        if self._tracer is not None:
            self._tracer.event(
                seq.seq_id, "handoff_ship",
                num_pages=info["num_pages"],
                kv_bytes=info["kv_bytes"])
        self.scheduler.finish_handoff(seq)

    def _handoff_kv_ready(self, seq: Sequence) -> Optional[bool]:
        """Availability of a parked handoff's KV. Pages ship in chain
        order, so probing the LAST shipped page (one HEAD at most)
        answers for the whole chain. True/False is definitive; None =
        tier unreachable (keep waiting until the handoff timeout).

        A cold-start probe (docs/kv_economy.md) asks a different
        question — "does the shared cache extend my local prefix?" —
        so it probes the FIRST page the local cache is missing: any
        hit there is a win (first-touch restore then pulls the longest
        available chain), and probing the last page would miss
        partially cached chains that are still worth restoring. The
        HEAD also records this engine's demand server-side, which is
        what promotes genuinely shared chains into the cache."""
        from production_stack_tpu.engine.kv_cache import (
            PagedCacheManager,
        )
        if seq.cold_start_probe:
            target = self._cold_start_target(seq)
            if target is None:
                return True  # local cache caught up meanwhile
            return self.offload.handoff_ready(target)
        usable = len(seq.prompt_token_ids) - 1
        hashes = PagedCacheManager.chain_hashes(
            seq.prompt_token_ids[:usable],
            self.cache_manager.page_size, seq.cache_salt)
        if not hashes:
            return True  # prompt shorter than a page: pure recompute
        return self.offload.handoff_ready(hashes[-1])

    def _admit_handoffs(self) -> None:
        """Flip AWAITING_KV sequences to WAITING once their pages are
        reachable (the normal first-touch restore path then pulls
        them), or degrade to recompute on definitive loss / timeout.
        Either way the request completes — never dropped."""
        now = time.time()
        with self._lock:
            for seq in list(self.scheduler.waiting):
                if seq.state != SequenceState.AWAITING_KV:
                    continue
                ready = self._handoff_kv_ready(seq)
                if ready is None and seq.cold_start_probe:
                    # Cold-start probes degrade immediately when the
                    # shared tier is down: nothing was shipped for
                    # this request, so waiting buys nothing — compute.
                    logger.debug(
                        "Cold-start probe %s: shared tier "
                        "unreachable; computing", seq.seq_id)
                elif ready is None:
                    if (now - seq.handoff_arrival_time
                            < self.config.handoff_timeout_s):
                        continue
                    logger.warning(
                        "Handoff %s timed out waiting for KV; "
                        "degrading to recompute", seq.seq_id)
                elif ready is False and not seq.cold_start_probe:
                    logger.warning(
                        "Handoff %s KV not in any offload tier; "
                        "degrading to recompute", seq.seq_id)
                seq.transition(SequenceState.WAITING)
                if not seq.cold_start_probe:
                    # Cold-start parks stay out of the disagg handoff
                    # admission histogram — they are routine admission
                    # probes, not handoff transfers.
                    self.metrics.on_handoff_admitted(
                        now - seq.handoff_arrival_time)
                if self._tracer is not None:
                    self._tracer.event(
                        seq.seq_id, "awaiting_kv_restore",
                        waited_ms=round(
                            (now - seq.handoff_arrival_time) * 1e3, 2),
                        outcome=("ready" if ready
                                 else "tier_down"
                                 if ready is None and seq.cold_start_probe
                                 else "timeout" if ready is None
                                 else "miss" if seq.cold_start_probe
                                 else "lost"))

    def register_lora(self, name_or_path: str,
                      name: Optional[str] = None) -> int:
        """Load + install a PEFT adapter; serve it under ``name``."""
        if self.runner.lora_registry is None:
            raise ValueError("LoRA is not enabled on this engine")
        from production_stack_tpu.engine.lora import load_peft_adapter
        adapter = load_peft_adapter(
            name_or_path, self.config.model,
            self.config.lora.max_lora_rank, name=name,
        )
        with self._lock:
            return self.runner.lora_registry.register(adapter)

    def lora_names(self) -> List[str]:
        if self.runner.lora_registry is None:
            return []
        return self.runner.lora_registry.names()

    def abort_request(self, seq_id: str) -> None:
        with self._lock:
            seq = self.sequences.pop(seq_id, None)
            if seq is not None:
                self.scheduler.abort_sequence(seq)
                self.metrics.on_finished(seq)
                self._drop_checkpoint_state(seq_id)
                if self._tracer is not None:
                    self._trace_finish(seq)

    def _trace_finish(self, seq: Sequence) -> None:
        """Finalize ``seq``'s engine span (caller checked the tracer)."""
        self._tracer.finish(
            seq.seq_id,
            reason=(seq.finish_reason.value
                    if seq.finish_reason else None),
            arrival_ts=seq.arrival_time,
            first_scheduled_ts=seq.first_scheduled_time,
            first_token_ts=seq.first_token_time,
            finish_ts=seq.finish_time,
            prompt_tokens=seq.num_prompt_tokens,
            output_tokens=seq.num_generated)

    def has_work(self) -> bool:
        # A dispatched-but-unread decode step is work: the loop must
        # come back to reconcile it even if every row since finished.
        return self._in_flight is not None or self.scheduler.has_work()

    # ---- engine step ------------------------------------------------------

    def step(self) -> List[StepOutput]:
        """Plan + execute one device program; returns per-seq deltas.

        ``scheduler.async_scheduling`` routes decode through the
        overlapped plan -> dispatch -> complete pipeline
        (docs/async_pipeline.md): step N+1 is planned and dispatched
        before step N's tokens are read back, hiding scheduler/commit
        host work behind the device step. Single-host only — the
        multihost step bridge broadcasts host-resident numpy payloads.
        """
        if self.scheduler.num_awaiting_kv:
            self._admit_handoffs()
        if self.config.checkpoint_interval_tokens > 0:
            self._checkpoint_tick()
        if (self.config.scheduler.async_scheduling
                and self.runner.bridge is None):
            return self._step_async()
        return self._step_sync()

    def _plan_locked(self, outputs: List[StepOutput]):
        with self._lock:
            plan = self.scheduler.plan_step()
            for seq in self.scheduler.newly_aborted:
                outputs.append(self._delta(seq, None))
            self.scheduler.newly_aborted.clear()
        return plan

    def _step_sync(self) -> List[StepOutput]:
        outputs: List[StepOutput] = []
        t0 = time.perf_counter()
        plan = self._plan_locked(outputs)
        if plan.empty:
            for out in outputs:
                self.sequences.pop(out.seq_id, None)
            return outputs
        if plan.prefill is not None and plan.decode is not None:
            # Mixed plan (scheduler._plan_mixed): one unified ragged
            # dispatch carries both sides (docs/unified_step.md).
            wait_s = self._execute_unified(plan, outputs)
        elif plan.prefill is not None:
            wait_s = self._execute_prefill(plan, outputs)
        else:
            wait_s = self._execute_decode_sync(plan, outputs)
        self._account_step(
            host_s=(time.perf_counter() - t0) - wait_s,
            wait_s=wait_s, ahead=False)
        self._pop_finished(outputs)
        return outputs

    def _account_step(self, host_s: float, wait_s: float, ahead: bool,
                      pipeline_break: bool = False, **extra) -> None:
        """One step's accounting fan-out: the aggregate pipeline
        metrics, plus a flight-recorder record (engine/tracing.py)
        carrying the row/spec note the execute helper staged."""
        self.metrics.on_pipeline_step(
            host_s=host_s, device_wait_s=wait_s, ahead=ahead)
        obs_note = self._obs_note
        if obs_note is not None:
            self._obs_note = None
            obs = getattr(self.runner, "observatory", None)
            if obs is not None:
                obs.on_step(obs_note[0], wait_s, obs_note[1])
        if self._tracer is not None:
            note = self._step_note or {}
            self._step_note = None
            note.update(extra)
            self._tracer.on_step(
                host_ms=round(host_s * 1e3, 3),
                device_wait_ms=round(wait_s * 1e3, 3),
                ahead=ahead, pipeline_break=pipeline_break, **note)

    def _execute_prefill(self, plan, outputs) -> float:
        td = time.perf_counter()
        self._note_dispatch(td)
        sampled, lp_rows = self.runner.run_prefill(plan.prefill)
        tr = time.perf_counter()
        self._idle_mark = tr
        with self._lock:
            for i, (chunk, token) in enumerate(
                    zip(plan.prefill.chunks, sampled)):
                self.scheduler.on_prefill_executed(chunk, token)
                if chunk.is_last_chunk:
                    if (chunk.seq.handoff_prefill
                            and chunk.seq.state
                            == SequenceState.RUNNING):
                        # Disagg prefill role: ship KV + retire
                        # (unless the first token already finished
                        # the request — then there is nothing to
                        # decode and nothing worth shipping).
                        self._ship_handoff(chunk.seq)
                    outputs.append(self._delta(
                        chunk.seq, token,
                        lp_rows[i] if lp_rows else None))
            if self._tracer is not None:
                self._step_note = {
                    "kind": "prefill",
                    "prefill_rows": len(plan.prefill.chunks),
                    "row_bucket": self.runner.prefill_width,
                }
        self._obs_note = ("prefill",
                          sum(len(c.chunk_tokens)
                              for c in plan.prefill.chunks))
        return tr - td

    def _execute_decode_sync(self, plan, outputs) -> float:
        td = time.perf_counter()
        self._note_dispatch(td)
        token_lists, lp_lists = self.runner.run_decode(plan.decode)
        tr = time.perf_counter()
        self._idle_mark = tr
        now = time.time()
        spec_drafts = plan.decode.drafts
        with self._lock:
            drafted = accepted = step_tokens = 0
            for i, (seq, toks) in enumerate(
                    zip(plan.decode.seqs, token_lists)):
                if spec_drafts is not None:
                    # Device-level acceptance (each verify row
                    # emits accepted + 1 tokens), counted before
                    # any host-side stop truncation so the rate
                    # reflects the model, not request budgets.
                    drafted += len(spec_drafts[i])
                    accepted += len(toks) - 1
                    seq.spec_drafted_total += len(spec_drafts[i])
                    seq.spec_accepted_total += max(0, len(toks) - 1)
                emitted = 0
                for k, tok in enumerate(toks):
                    if seq.state != SequenceState.RUNNING:
                        break  # stop hit mid-window: drop the tail
                    self.scheduler.append_decode_token(seq, tok)
                    emitted += 1
                    outputs.append(self._delta(
                        seq, tok,
                        lp_lists[i][k] if lp_lists else None))
                step_tokens += emitted
                self.metrics.on_decode_tokens(seq, emitted, now)
                if spec_drafts is not None:
                    self.scheduler.on_spec_executed(seq)
            if spec_drafts is not None:
                self.metrics.on_spec_step(drafted, accepted)
            if self._tracer is not None:
                self._step_note = {
                    "kind": "spec" if spec_drafts is not None
                    else "decode",
                    "decode_rows": len(plan.decode.seqs),
                    "row_bucket": self.runner.decode_width,
                    "window": plan.decode.window,
                    "spec_drafted": drafted,
                    "spec_accepted": accepted,
                }
        self._obs_note = ("spec" if spec_drafts is not None
                          else "decode", step_tokens)
        return tr - td

    def _execute_unified(self, plan, outputs) -> float:
        """One unified ragged step (docs/unified_step.md): decode/
        draft rows and prefill chunk rows commit out of a single
        dispatch — decode rows through the spec-verify contract
        (1..span tokens each), prefill chunks through the ordinary
        chunked-prefill commit path, handoff shipping included."""
        td = time.perf_counter()
        self._note_dispatch(td)
        (token_lists, lp_lists, prefill_toks,
         prefill_lps) = self.runner.run_unified(plan)
        tr = time.perf_counter()
        self._idle_mark = tr
        now = time.time()
        seqs = plan.decode.seqs[: self.runner.decode_width]
        chunks = plan.prefill.chunks[: self.runner.prefill_width]
        spec_drafts = plan.decode.drafts
        self.metrics.on_ragged_step(
            prefill_rows=len(chunks), decode_rows=len(seqs),
            pad_rows=(self.runner.last_unified_rows
                      - len(chunks) - len(seqs)))
        with self._lock:
            drafted = accepted = step_tokens = 0
            for i, (seq, toks) in enumerate(zip(seqs, token_lists)):
                if spec_drafts is not None:
                    drafted += len(spec_drafts[i])
                    accepted += len(toks) - 1
                    seq.spec_drafted_total += len(spec_drafts[i])
                    seq.spec_accepted_total += max(0, len(toks) - 1)
                emitted = 0
                for k, tok in enumerate(toks):
                    if seq.state != SequenceState.RUNNING:
                        break  # stop hit mid-span: drop the tail
                    self.scheduler.append_decode_token(seq, tok)
                    emitted += 1
                    outputs.append(self._delta(
                        seq, tok,
                        lp_lists[i][k] if lp_lists else None))
                step_tokens += emitted
                self.metrics.on_decode_tokens(seq, emitted, now)
                if spec_drafts is not None:
                    self.scheduler.on_spec_executed(seq)
            if spec_drafts is not None:
                self.metrics.on_spec_step(drafted, accepted)
            for i, (chunk, token) in enumerate(
                    zip(chunks, prefill_toks)):
                self.scheduler.on_prefill_executed(chunk, token)
                if chunk.is_last_chunk:
                    if (chunk.seq.handoff_prefill
                            and chunk.seq.state
                            == SequenceState.RUNNING):
                        self._ship_handoff(chunk.seq)
                    outputs.append(self._delta(
                        chunk.seq, token,
                        prefill_lps[i] if prefill_lps else None))
            if self._tracer is not None:
                self._step_note = {
                    "kind": "unified",
                    "prefill_rows": len(chunks),
                    "decode_rows": len(seqs),
                    "pad_rows": (self.runner.last_unified_rows
                                 - len(chunks) - len(seqs)),
                    "row_bucket": self.runner.last_unified_rows,
                    "window": plan.decode.window,
                    "spec_drafted": drafted,
                    "spec_accepted": accepted,
                }
        self._obs_note = ("unified",
                          step_tokens + sum(len(c.chunk_tokens)
                                            for c in chunks))
        return tr - td

    # ---- overlapped async pipeline (docs/async_pipeline.md) ---------------

    def _step_async(self) -> List[StepOutput]:
        """One pipeline turn, depth 1: when a decode step is in
        flight, plan and dispatch its successor BEFORE reading its
        results. The successor consumes the in-flight step's
        sampled-token device array directly (DecodeStepHandle
        .token_source), so the device starts step N+1 while the host
        is still committing step N's tokens."""
        handle = self._in_flight
        if handle is not None:
            t0 = time.perf_counter()
            rows = None
            if handle.expected_lens is None:
                with self._lock:
                    rows = self.scheduler.plan_ahead(handle.rows)
            # else: this handle is the assume-1 successor of a spec
            # verify step. Complete it now with the stale-drop filter
            # (_complete) and re-plan from fresh host state — chaining
            # another step off a possibly-stale token source can never
            # recover, as every successor would sample from the same
            # incomplete context (docs/unified_step.md
            # §spec-under-async).
            if rows is not None:
                nxt = self.runner.dispatch_decode(
                    rows, token_source=handle.token_source,
                    ahead=True)
                if handle.is_spec:
                    # The successor assumed each row commits exactly
                    # one token; record the total_len that assumption
                    # predicts so _complete can drop rows where the
                    # verify committed more (its KV write is identical
                    # either way — token_source is always the first
                    # committed token).
                    nxt.expected_lens = [
                        None if seq is None else seq.total_len + 1
                        for seq in rows]
                self._in_flight = nxt
                outputs, wait_s = self._complete(handle)
                # No _idle_mark here: step N+1 was queued before step
                # N's results were read — the device never idled.
                self._account_step(
                    host_s=(time.perf_counter() - t0) - wait_s,
                    wait_s=wait_s, ahead=True)
                return outputs
            # Pipeline break (prefill waiting / ineligible row / no
            # boundary pages): drain the in-flight step, then let the
            # next step() re-plan synchronously with full knowledge.
            self._in_flight = None
            self.metrics.set_inflight_depth(0)
            outputs, wait_s = self._complete(handle)
            self._idle_mark = time.perf_counter()
            self._account_step(
                host_s=(time.perf_counter() - t0) - wait_s,
                wait_s=wait_s, ahead=False, pipeline_break=True)
            return outputs
        outputs: List[StepOutput] = []
        t0 = time.perf_counter()
        plan = self._plan_locked(outputs)
        if plan.empty:
            for out in outputs:
                self.sequences.pop(out.seq_id, None)
            return outputs
        if plan.prefill is not None:
            # Prefill (and the mixed ragged step) stays synchronous:
            # each chunk's commit feeds the next chunk's plan, so
            # these run as deliberate pipeline breaks.
            if plan.decode is not None:
                wait_s = self._execute_unified(plan, outputs)
            else:
                wait_s = self._execute_prefill(plan, outputs)
            self._account_step(
                host_s=(time.perf_counter() - t0) - wait_s,
                wait_s=wait_s, ahead=False, pipeline_break=True)
            self._pop_finished(outputs)
            return outputs
        if plan.decode.drafts is not None:
            # Speculative verify step: dispatch it in flight like a
            # decode step — its commit count is data-dependent, so
            # the NEXT turn's ahead dispatch assumes one token and
            # reconciles via the expected_lens stale-drop path
            # (docs/unified_step.md §spec-under-async).
            self._note_dispatch(time.perf_counter())
            self._in_flight = self.runner.dispatch_spec(plan.decode)
            self.metrics.set_inflight_depth(1)
            self._account_step(
                host_s=time.perf_counter() - t0, wait_s=0.0,
                ahead=False, kind="spec_dispatch")
            self._pop_finished(outputs)
            return outputs
        if plan.decode.window > 1:
            # Multi-step burst: the burst program already hides host
            # work for window-1 of its steps, so it runs synchronously
            # rather than through the depth-1 pipeline (stacking both
            # overlaps would speculate window tokens ahead).
            wait_s = self._execute_decode_sync(plan, outputs)
            self._account_step(
                host_s=(time.perf_counter() - t0) - wait_s,
                wait_s=wait_s, ahead=False)
            self._pop_finished(outputs)
            return outputs
        # Single-step pure-decode plan: dispatch and return without
        # waiting; the next turn plans ahead against it.
        self._note_dispatch(time.perf_counter())
        self._in_flight = self.runner.dispatch_decode(
            plan.decode.seqs[: self.runner.decode_width])
        self.metrics.set_inflight_depth(1)
        self._account_step(
            host_s=time.perf_counter() - t0, wait_s=0.0,
            ahead=False, kind="decode_dispatch")
        self._pop_finished(outputs)
        return outputs

    def _complete(self, handle) -> tuple:
        """Read back + reconcile one dispatched decode or verify
        step: commit tokens through the same scheduler path as the
        sync loop. Rows that finished or were aborted mid-flight
        break out exactly as there; plan-ahead boundary pages ride
        seq.pages and return through the ordinary free_sequence path,
        so a mid-flight abort leaks nothing. Handles carrying
        ``expected_lens`` (the assume-1 successor of a verify step)
        drop rows whose committed length diverged from the
        assumption — the stale-token path of
        docs/unified_step.md §spec-under-async."""
        tw = time.perf_counter()
        token_lists, lp_lists = handle.result()
        wait_s = time.perf_counter() - tw
        now = time.time()
        outputs: List[StepOutput] = []
        expected = handle.expected_lens
        spec_drafts = handle.drafts if handle.is_spec else None
        with self._lock:
            drafted = accepted = step_tokens = 0
            for i, (seq, toks) in enumerate(
                    zip(handle.rows, token_lists)):
                if seq is None:  # plan-ahead masked slot
                    continue
                if expected is not None and (
                        expected[i] is None
                        or seq.total_len != expected[i]):
                    # Stale: the verify step this row was dispatched
                    # behind committed more than the one token the
                    # ahead plan assumed, so this sample came from
                    # incomplete context. Its KV write was identical
                    # either way (token_source is always the first
                    # committed token) — only the sample is dropped.
                    continue
                if spec_drafts is not None:
                    drafted += len(spec_drafts[i])
                    accepted += len(toks) - 1
                    seq.spec_drafted_total += len(spec_drafts[i])
                    seq.spec_accepted_total += max(0, len(toks) - 1)
                emitted = 0
                for k, tok in enumerate(toks):
                    if seq.state != SequenceState.RUNNING:
                        break
                    self.scheduler.append_decode_token(seq, tok)
                    emitted += 1
                    outputs.append(self._delta(
                        seq, tok,
                        lp_lists[i][k] if lp_lists else None))
                step_tokens += emitted
                self.metrics.on_decode_tokens(seq, emitted, now)
                if spec_drafts is not None:
                    self.scheduler.on_spec_executed(seq)
            if spec_drafts is not None:
                self.metrics.on_spec_step(drafted, accepted)
            if self._tracer is not None:
                self._step_note = {
                    "kind": "spec" if handle.is_spec else "decode",
                    "decode_rows": sum(
                        1 for seq in handle.rows if seq is not None),
                    "row_bucket": self.runner.decode_width,
                    "spec_drafted": drafted,
                    "spec_accepted": accepted,
                }
        self._obs_note = ("spec" if handle.is_spec else "decode",
                          step_tokens)
        self._pop_finished(outputs)
        return outputs, wait_s

    def _pop_finished(self, outputs: List[StepOutput]) -> None:
        for out in outputs:
            if out.finished:
                seq = self.sequences.pop(out.seq_id, None)
                if seq is not None:
                    self.metrics.on_finished(seq)
                    self._drop_checkpoint_state(out.seq_id)
                    if self._tracer is not None:
                        self._trace_finish(seq)

    def _note_dispatch(self, now: float) -> None:
        """Device-idle accounting: accumulate the gap between the
        device draining its queue and the next dispatch."""
        if self._idle_mark is not None:
            self.metrics.on_device_idle(now - self._idle_mark)
            self._idle_mark = None

    def _delta(self, seq: Sequence, token: Optional[int],
               logprobs: Optional[tuple] = None) -> StepOutput:
        finished = seq.state in (
            SequenceState.FINISHED, SequenceState.ABORTED
        )
        return StepOutput(
            seq_id=seq.seq_id,
            new_token=token,
            finished=finished,
            finish_reason=(seq.finish_reason.value
                           if seq.finish_reason else None),
            logprobs=logprobs,
        )

    def _guided_advance(self, seq, token: int) -> None:
        """Host mirror of the device automaton carry (scheduler hook);
        tokens the automaton rejects (possible only via host-enforced
        stop-set overflow) freeze the state rather than corrupt it."""
        ns = self.guided_fsm.advance(seq.fsm_state, token)
        if ns >= 0:
            seq.fsm_state = ns

    # ---- metrics ----------------------------------------------------------

    def stats(self) -> Dict[str, float]:
        out = {
            "num_requests_running": self.scheduler.num_running,
            "num_requests_waiting": self.scheduler.num_waiting,
            "gpu_cache_usage_perc": self.cache_manager.usage_perc(),
            "gpu_prefix_cache_hit_rate":
                self.cache_manager.prefix_hit_rate(),
            "num_preemptions_total": self.scheduler.num_preemptions,
            "spec_decode_num_draft_tokens_total":
                self.metrics.spec_draft_tokens_total,
            "spec_decode_num_accepted_tokens_total":
                self.metrics.spec_accepted_tokens_total,
            "engine_step_host_seconds_total":
                self.metrics.step_host_seconds_total,
            "engine_step_device_wait_seconds_total":
                self.metrics.step_device_wait_seconds_total,
            "engine_device_idle_seconds_total":
                self.metrics.device_idle_seconds_total,
            "engine_pipeline_steps_total":
                self.metrics.pipeline_steps_total,
            "engine_pipeline_ahead_steps_total":
                self.metrics.pipeline_ahead_steps_total,
            "engine_async_inflight_depth":
                self.metrics.async_inflight_depth,
            # Unified ragged step occupancy (docs/unified_step.md):
            # last mixed dispatch's row split plus cumulative totals
            # for pad-ratio accounting (benchmarks ragged_pad_ratio).
            "engine_step_prefill_rows":
                self.metrics.last_prefill_rows,
            "engine_step_decode_rows":
                self.metrics.last_decode_rows,
            "engine_step_pad_rows": self.metrics.last_pad_rows,
            "engine_ragged_steps_total":
                self.metrics.ragged_steps_total,
            "engine_ragged_rows_total":
                self.metrics.ragged_rows_total,
            "engine_ragged_pad_rows_total":
                self.metrics.ragged_pad_rows_total,
            # KV quantization telemetry (docs/kv_quantization.md):
            # post-expansion page budget and worst-case KV bytes a
            # full decode batch writes per step.
            "engine_kv_cache_page_capacity":
                self.config.cache.num_pages - 1,
            "engine_kv_bytes_per_decode_step":
                self.config.scheduler.max_num_seqs
                * self.config.cache.kv_bytes_per_token(
                    self.config.model),
            # Disaggregated serving (docs/disaggregation.md).
            "disagg_prefill_requests_total":
                self.disagg_prefill_requests,
            "disagg_decode_requests_total":
                self.disagg_decode_requests,
            "disagg_kv_bytes_shipped_total":
                self.disagg_kv_bytes_shipped,
            "disagg_awaiting_kv_requests":
                self.scheduler.num_awaiting_kv,
            # Mid-stream crash safety (docs/crash_recovery.md).
            "checkpoint_ships_total": self.checkpoint_ships,
            "checkpoint_kv_bytes_total": self.checkpoint_kv_bytes,
            "stream_resumes_total": self.stream_resumes,
        }
        if self.offload is not None:
            out.update({
                f"kv_offload_{k}": v
                for k, v in self.offload.stats().items()
            })
        return out

    # ---- convenience ------------------------------------------------------

    def generate(self, prompt_token_ids: List[int],
                 sampling: Optional[SamplingParams] = None,
                 lora_name: Optional[str] = None,
                 ) -> Sequence:
        """Blocking single-prompt generation (tests/benchmarks)."""
        seq_id = self.add_request(prompt_token_ids, sampling,
                                  lora_name=lora_name)
        seq = self.sequences[seq_id]
        while seq.state not in (SequenceState.FINISHED,
                                SequenceState.ABORTED):
            if not self.step():
                time.sleep(0)
        return seq

    def generate_batch(self, prompts: List[List[int]],
                       sampling: Optional[SamplingParams] = None,
                       ) -> List[Sequence]:
        seqs = []
        for p in prompts:
            sp = (SamplingParams(**vars(sampling))
                  if sampling else SamplingParams())
            seq_id = self.add_request(p, sp)
            seqs.append(self.sequences[seq_id])
        while any(s.state not in (SequenceState.FINISHED,
                                  SequenceState.ABORTED) for s in seqs):
            self.step()
        return seqs
