"""HF checkpoint loading: safetensors/torch-bin -> stacked JAX pytrees.

Weight names follow the HF conventions for Llama
(model.layers.N.self_attn.q_proj.weight, ...) and OPT
(model.decoder.layers.N....). Per-layer tensors are stacked along a
leading L axis to match the scanned-layer model layout
(models/llama.py). Linear weights are transposed: HF stores [out, in],
our matmuls use [in, out].
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from production_stack_tpu.engine.config import ModelConfig
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)


def _load_raw_tensors(model_dir: str) -> Dict[str, np.ndarray]:
    tensors: Dict[str, np.ndarray] = {}
    st_files = sorted(
        f for f in os.listdir(model_dir) if f.endswith(".safetensors")
    )
    if st_files:
        from safetensors.numpy import load_file
        for f in st_files:
            tensors.update(load_file(os.path.join(model_dir, f)))
        return tensors
    bin_files = sorted(
        f for f in os.listdir(model_dir)
        if f.endswith(".bin") and f.startswith("pytorch_model")
    )
    if bin_files:
        import torch
        for f in bin_files:
            state = torch.load(
                os.path.join(model_dir, f), map_location="cpu",
                weights_only=True,
            )
            for k, v in state.items():
                tensors[k] = v.float().numpy()
        return tensors
    raise FileNotFoundError(
        f"No safetensors/pytorch_model.bin found in {model_dir}"
    )


def load_model_config(model_dir: str,
                      name: str = "") -> ModelConfig:
    with open(os.path.join(model_dir, "config.json")) as f:
        hf = json.load(f)
    return ModelConfig.from_hf_config(hf, name=name or model_dir)


def _stack(tensors: Dict[str, np.ndarray], template: str, layers: int,
           transpose: bool = False) -> np.ndarray:
    parts = []
    for i in range(layers):
        t = tensors[template.format(i)]
        parts.append(t.T if transpose else t)
    return np.stack(parts)


def load_llama_weights(model_dir: str, config: ModelConfig,
                       dtype=None) -> Dict[str, jnp.ndarray]:
    raw = _load_raw_tensors(model_dir)
    raw = {k.removeprefix("model."): v for k, v in raw.items()}
    L = config.num_hidden_layers
    dtype = dtype or config.jax_dtype

    def lt(template, transpose=True):
        return jnp.asarray(
            _stack(raw, template, L, transpose=transpose), dtype
        )

    params = {
        "embed": jnp.asarray(raw["embed_tokens.weight"], dtype),
        "final_norm": jnp.asarray(raw["norm.weight"], dtype),
        "attn_norm": lt("layers.{}.input_layernorm.weight",
                        transpose=False),
        "wq": lt("layers.{}.self_attn.q_proj.weight"),
        "wk": lt("layers.{}.self_attn.k_proj.weight"),
        "wv": lt("layers.{}.self_attn.v_proj.weight"),
        "wo": lt("layers.{}.self_attn.o_proj.weight"),
        "mlp_norm": lt("layers.{}.post_attention_layernorm.weight",
                       transpose=False),
        "w_gate": lt("layers.{}.mlp.gate_proj.weight"),
        "w_up": lt("layers.{}.mlp.up_proj.weight"),
        "w_down": lt("layers.{}.mlp.down_proj.weight"),
    }
    if config.attention_bias:  # Qwen2-style q/k/v biases
        params["bq"] = lt("layers.{}.self_attn.q_proj.bias", False)
        params["bk"] = lt("layers.{}.self_attn.k_proj.bias", False)
        params["bv"] = lt("layers.{}.self_attn.v_proj.bias", False)
    if not config.tie_word_embeddings:
        head = raw.get("lm_head.weight")
        if head is None:
            config.tie_word_embeddings = True
        else:
            params["lm_head"] = jnp.asarray(head.T, dtype)
    return params


def load_gpt2_weights(model_dir: str, config: ModelConfig,
                      dtype=None) -> Dict[str, jnp.ndarray]:
    """HF GPT-2 checkpoints use Conv1D layout ([in, out], no transpose)
    and a fused qkv projection (``c_attn``), split here so the runtime
    shares the llama-family attention path."""
    raw = _load_raw_tensors(model_dir)
    raw = {k.removeprefix("transformer."): v for k, v in raw.items()}
    L = config.num_hidden_layers
    h = config.hidden_size
    dtype = dtype or config.jax_dtype

    def lt(template, transpose=False):
        return jnp.asarray(
            _stack(raw, template, L, transpose=transpose), dtype
        )

    qkv_w = _stack(raw, "h.{}.attn.c_attn.weight", L)   # [L, h, 3h]
    qkv_b = _stack(raw, "h.{}.attn.c_attn.bias", L)     # [L, 3h]
    return {
        "embed": jnp.asarray(raw["wte.weight"], dtype),
        "pos_embed": jnp.asarray(raw["wpe.weight"], dtype),
        "final_norm_w": jnp.asarray(raw["ln_f.weight"], dtype),
        "final_norm_b": jnp.asarray(raw["ln_f.bias"], dtype),
        "attn_norm_w": lt("h.{}.ln_1.weight"),
        "attn_norm_b": lt("h.{}.ln_1.bias"),
        "wq": jnp.asarray(qkv_w[:, :, 0 * h:1 * h], dtype),
        "bq": jnp.asarray(qkv_b[:, 0 * h:1 * h], dtype),
        "wk": jnp.asarray(qkv_w[:, :, 1 * h:2 * h], dtype),
        "bk": jnp.asarray(qkv_b[:, 1 * h:2 * h], dtype),
        "wv": jnp.asarray(qkv_w[:, :, 2 * h:3 * h], dtype),
        "bv": jnp.asarray(qkv_b[:, 2 * h:3 * h], dtype),
        "wo": lt("h.{}.attn.c_proj.weight"),
        "bo": lt("h.{}.attn.c_proj.bias"),
        "mlp_norm_w": lt("h.{}.ln_2.weight"),
        "mlp_norm_b": lt("h.{}.ln_2.bias"),
        "fc1": lt("h.{}.mlp.c_fc.weight"),
        "fc1_b": lt("h.{}.mlp.c_fc.bias"),
        "fc2": lt("h.{}.mlp.c_proj.weight"),
        "fc2_b": lt("h.{}.mlp.c_proj.bias"),
    }


def load_opt_weights(model_dir: str, config: ModelConfig,
                     dtype=None) -> Dict[str, jnp.ndarray]:
    raw = _load_raw_tensors(model_dir)
    raw = {
        k.removeprefix("model.").removeprefix("decoder."): v
        for k, v in raw.items()
    }
    L = config.num_hidden_layers
    dtype = dtype or config.jax_dtype

    def lt(template, transpose=True):
        return jnp.asarray(
            _stack(raw, template, L, transpose=transpose), dtype
        )

    return {
        "embed": jnp.asarray(raw["embed_tokens.weight"], dtype),
        "pos_embed": jnp.asarray(raw["embed_positions.weight"], dtype),
        "final_norm_w": jnp.asarray(raw["final_layer_norm.weight"], dtype),
        "final_norm_b": jnp.asarray(raw["final_layer_norm.bias"], dtype),
        "attn_norm_w": lt("layers.{}.self_attn_layer_norm.weight", False),
        "attn_norm_b": lt("layers.{}.self_attn_layer_norm.bias", False),
        "wq": lt("layers.{}.self_attn.q_proj.weight"),
        "bq": lt("layers.{}.self_attn.q_proj.bias", False),
        "wk": lt("layers.{}.self_attn.k_proj.weight"),
        "bk": lt("layers.{}.self_attn.k_proj.bias", False),
        "wv": lt("layers.{}.self_attn.v_proj.weight"),
        "bv": lt("layers.{}.self_attn.v_proj.bias", False),
        "wo": lt("layers.{}.self_attn.out_proj.weight"),
        "bo": lt("layers.{}.self_attn.out_proj.bias", False),
        "mlp_norm_w": lt("layers.{}.final_layer_norm.weight", False),
        "mlp_norm_b": lt("layers.{}.final_layer_norm.bias", False),
        "fc1": lt("layers.{}.fc1.weight"),
        "fc1_b": lt("layers.{}.fc1.bias", False),
        "fc2": lt("layers.{}.fc2.weight"),
        "fc2_b": lt("layers.{}.fc2.bias", False),
    }


def load_mixtral_weights(model_dir: str, config: ModelConfig,
                         dtype=None) -> Dict[str, jnp.ndarray]:
    """HF Mixtral: llama-style attention + per-expert SwiGLU weights
    (block_sparse_moe.experts.{e}.w1/w3/w2 = gate/up/down, all [out,
    in]) stacked to [L, E, in, out]."""
    raw = _load_raw_tensors(model_dir)
    raw = {k.removeprefix("model."): v for k, v in raw.items()}
    L = config.num_hidden_layers
    E = config.num_local_experts
    dtype = dtype or config.jax_dtype

    def lt(template, transpose=True):
        return jnp.asarray(
            _stack(raw, template, L, transpose=transpose), dtype
        )

    def experts(which):  # w1 | w2 | w3
        per_layer = []
        for i in range(L):
            per_expert = [
                raw[f"layers.{i}.block_sparse_moe.experts.{e}"
                    f".{which}.weight"].T
                for e in range(E)
            ]
            per_layer.append(np.stack(per_expert))
        return jnp.asarray(np.stack(per_layer), dtype)  # [L,E,in,out]

    params = {
        "embed": jnp.asarray(raw["embed_tokens.weight"], dtype),
        "final_norm": jnp.asarray(raw["norm.weight"], dtype),
        "attn_norm": lt("layers.{}.input_layernorm.weight", False),
        "wq": lt("layers.{}.self_attn.q_proj.weight"),
        "wk": lt("layers.{}.self_attn.k_proj.weight"),
        "wv": lt("layers.{}.self_attn.v_proj.weight"),
        "wo": lt("layers.{}.self_attn.o_proj.weight"),
        "mlp_norm": lt("layers.{}.post_attention_layernorm.weight",
                       False),
        "moe_gate": lt("layers.{}.block_sparse_moe.gate.weight"),
        "w_gate": experts("w1"),
        "w_up": experts("w3"),
        "w_down": experts("w2"),
    }
    head = raw.get("lm_head.weight")
    if head is None:
        config.tie_word_embeddings = True
    else:
        params["lm_head"] = jnp.asarray(head.T, dtype)
    return params


def load_weights(model_dir: str, config: ModelConfig,
                 dtype=None) -> Dict[str, jnp.ndarray]:
    if config.architecture == "opt":
        return load_opt_weights(model_dir, config, dtype)
    if config.architecture == "gpt2":
        return load_gpt2_weights(model_dir, config, dtype)
    if config.architecture == "mixtral":
        return load_mixtral_weights(model_dir, config, dtype)
    return load_llama_weights(model_dir, config, dtype)
