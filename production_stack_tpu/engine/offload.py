"""KV cache offload tiers: TPU HBM -> host RAM -> remote cache server.

Capability parity with the reference's LMCache integration
(deployment-vllm-multi.yaml:158-182 env plumbing: LMCACHE_LOCAL_CPU,
LMCACHE_MAX_LOCAL_CPU_SIZE, LMCACHE_REMOTE_URL/SERDE; tutorials 05/06),
re-designed for TPU: KV pages move across tiers with
``jax.device_get``/``jax.device_put`` on page granularity — the JAX
device API is the DMA path, no CUDA pointers.

Tiers:
  1. HBM: the paged cache itself (kv_cache.PagedCacheManager).
  2. Host RAM: ``HostKVPool`` — content-hash-keyed numpy pages with an
     LRU byte budget (the LMCache "local_cpu" analogue).
  3. Remote: ``RemoteKVClient`` speaking the cache-server protocol
     (engine/cache_server.py) over DCN — the shared-KV tier multiple
     engine pods can hit (tutorial 06 analogue).

Pages are keyed by the same chain hash the prefix cache uses, so a
page restored from any tier is byte-identical to recomputing prefill.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from production_stack_tpu.engine.kv_cache import PageHash
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

# A page's KV payload: (k, v) each [L, kv_heads, head_dim, page_size]
# (the head-major cache layout, model_runner.read_page), or the
# quantized 4-tuple (k, v, k_scale, v_scale) with int8 data and
# [L, kv_heads, page_size] float32 scales. Tiers treat the payload as
# an opaque tuple of arrays; arity and dtypes round-trip verbatim.
PagePayload = Tuple[np.ndarray, ...]

# Wire-format version, folded into every tier key so pods running a
# different KV page layout (e.g. across a rolling upgrade against a
# shared remote cache) can never restore each other's bytes into the
# wrong axis order. Bump whenever PagePayload layout changes.
KV_WIRE_VERSION = 3

# Page dtypes a cache server will accept (engine/cache_server.py
# validates inbound payloads against this before storing them).
ALLOWED_WIRE_DTYPES = ("float32", "float16", "bfloat16", "int8")


def _np_dtype(name: str) -> np.dtype:
    """np.dtype from a wire name, including the ml_dtypes extensions.

    ``np.dtype("bfloat16")`` raises TypeError — bfloat16 is registered
    by ml_dtypes, not numpy — so bf16 pages coming back from the
    remote tier must resolve through the ml_dtypes namespace.
    """
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        try:
            return np.dtype(getattr(ml_dtypes, name))
        except AttributeError:
            raise TypeError(f"unsupported KV wire dtype {name!r}")


def _stable_key(page_hash: PageHash, kv_dtype: str = "") -> str:
    """Serializable, process-independent key for a chain hash.

    ``kv_dtype`` namespaces the key by page storage format so int8 and
    full-precision pods sharing a remote cache can never restore each
    other's payloads into a mismatched cache.
    """
    import hashlib
    parent, tokens = page_hash
    raw = (f"v{KV_WIRE_VERSION}:{kv_dtype}:{parent}:"
           f"{','.join(map(str, tokens))}").encode()
    return hashlib.sha256(raw).hexdigest()


class HostKVPool:
    """LRU pool of KV pages in host RAM.

    Eviction runs on watermark hysteresis (docs/kv_economy.md): a put
    that would push usage past ``watermark_high * max_bytes`` evicts
    oldest-first down to ``watermark_low * max_bytes``, so a full pool
    sheds a batch of cold pages once instead of evicting one page on
    every subsequent put. The defaults (1.0/1.0) preserve the legacy
    evict-exactly-at-capacity behavior.
    """

    def __init__(self, max_bytes: int = 2 * 1024 ** 3,
                 watermark_high: float = 1.0,
                 watermark_low: float = 1.0):
        if not 0.0 < watermark_low <= watermark_high <= 1.0:
            raise ValueError(
                "require 0 < watermark_low <= watermark_high <= 1, "
                f"got low={watermark_low} high={watermark_high}")
        self.max_bytes = max_bytes
        self.watermark_high = watermark_high
        self.watermark_low = watermark_low
        self._pool: "OrderedDict[str, PagePayload]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._pool)

    @property
    def used_bytes(self) -> int:
        return self._bytes

    def put(self, key: str, payload: PagePayload) -> None:
        size = sum(a.nbytes for a in payload)
        with self._lock:
            if key in self._pool:
                self._pool.move_to_end(key)
                return
            high = self.watermark_high * self.max_bytes
            low = self.watermark_low * self.max_bytes
            if self._bytes + size > high:
                while self._bytes + size > low and self._pool:
                    _, evicted = self._pool.popitem(last=False)
                    self._bytes -= sum(a.nbytes for a in evicted)
                    self.evictions += 1
            if self._bytes + size <= self.max_bytes:
                self._pool[key] = payload
                self._bytes += size

    def get(self, key: str) -> Optional[PagePayload]:
        with self._lock:
            payload = self._pool.get(key)
            if payload is not None:
                self._pool.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
            return payload

    def contains(self, key: str) -> bool:
        with self._lock:
            return key in self._pool


class RemoteKVClient:
    """Client for the remote shared KV cache server (DCN tier).

    Wire format (engine/cache_server.py): msgpack-framed binary over
    HTTP — PUT /kv/<key>, GET /kv/<key>, HEAD /kv/<key>.
    """

    def __init__(self, base_url: str, timeout_s: float = 5.0,
                 requester: str = ""):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        # Identity sent as X-KV-Requester so the managed cache can
        # count DISTINCT engines demanding a chain (admission by
        # demand promotion, kvecon/cluster_cache.py).
        self.requester = requester
        # Engine-side view of the shared tier, exported as the
        # vllm:kv_cluster_* counters (engine/server.py /metrics).
        self.hits = 0
        self.misses = 0
        self.admissions = 0
        self.rejections = 0
        import requests
        self._session = requests.Session()

    def _headers(self, chain: Optional[str] = None) -> Dict[str, str]:
        headers = {}
        if self.requester:
            headers["X-KV-Requester"] = self.requester
        if chain:
            headers["X-KV-Chain"] = chain
        return headers

    def put(self, key: str, payload: PagePayload,
            chain: Optional[str] = None) -> bool:
        import msgpack
        # Per-array framing: each page array carries its own
        # shape/dtype, so mixed-dtype payloads (int8 data + float32
        # scales) and bf16 pages serialize without a shared dtype.
        body = msgpack.packb({
            "version": KV_WIRE_VERSION,
            "arrays": [
                {"data": a.tobytes(), "shape": list(a.shape),
                 "dtype": str(a.dtype)}
                for a in payload
            ],
        })
        try:
            resp = self._session.put(
                f"{self.base_url}/kv/{key}", data=body,
                timeout=self.timeout_s,
                headers=self._headers(chain),
            )
            if resp.status_code != 200:
                return False
            # A managed cache answers 200 with an admission verdict;
            # {"admitted": false} means "not promoted yet, don't
            # bother retrying" and is SUCCESS for the write-through
            # hot path — the page stays in the host tier and the
            # server has recorded the demand. Legacy servers answer a
            # bare 200 body; treat that as admitted.
            try:
                verdict = resp.json()
            except ValueError:
                verdict = {}
            if isinstance(verdict, dict) and \
                    verdict.get("admitted") is False:
                self.rejections += 1
            else:
                self.admissions += 1
            return True
        except Exception as e:
            logger.warning("Remote KV put failed: %s", e)
            return False

    def get(self, key: str) -> Optional[PagePayload]:
        import msgpack
        try:
            resp = self._session.get(
                f"{self.base_url}/kv/{key}", timeout=self.timeout_s,
                headers=self._headers(),
            )
            if resp.status_code != 200:
                self.misses += 1
                return None
            obj = msgpack.unpackb(resp.content)
            self.hits += 1
            return tuple(
                np.frombuffer(a["data"], _np_dtype(a["dtype"]))
                .reshape(tuple(a["shape"]))
                for a in obj["arrays"]
            )
        except Exception as e:
            logger.warning("Remote KV get failed: %s", e)
            return None

    def contains(self, key: str) -> bool:
        return self.probe(key) is True

    def probe(self, key: str) -> Optional[bool]:
        """``contains`` with errors distinguished: True/False is a
        definitive server answer, None a transport failure (tier
        unreachable right now). Disagg handoff admission
        (engine._admit_handoffs) degrades to recompute immediately on
        False but keeps waiting (until the handoff timeout) on None."""
        try:
            resp = self._session.head(
                f"{self.base_url}/kv/{key}", timeout=self.timeout_s,
                headers=self._headers(),
            )
            return resp.status_code == 200
        except Exception:
            return None

    def batch_get(self, keys: List[str]) -> Dict[str, PagePayload]:
        """Fetch many pages in one round trip (POST /kv/batch_get).

        Returns only the keys the server holds; falls back to
        sequential GETs against an older server that lacks the
        endpoint. The response carries the exact blobs stored at PUT
        (already validated server-side); the dtype allowlist is
        re-checked here before any buffer is interpreted.
        """
        import msgpack
        if not keys:
            return {}
        try:
            resp = self._session.post(
                f"{self.base_url}/kv/batch_get",
                data=msgpack.packb({"keys": list(keys)}),
                timeout=self.timeout_s,
                headers=self._headers(),
            )
            if resp.status_code in (404, 405):
                out = {}
                for key in keys:
                    payload = self.get(key)
                    if payload is not None:
                        out[key] = payload
                return out
            if resp.status_code != 200:
                return {}
            obj = msgpack.unpackb(resp.content)
            blobs = obj.get("blobs")
            if not isinstance(blobs, list) or len(blobs) != len(keys):
                return {}
            out = {}
            for key, blob in zip(keys, blobs):
                if blob is None:
                    continue
                arrays = msgpack.unpackb(blob)["arrays"]
                if any(a["dtype"] not in ALLOWED_WIRE_DTYPES
                       for a in arrays):
                    continue
                out[key] = tuple(
                    np.frombuffer(a["data"], _np_dtype(a["dtype"]))
                    .reshape(tuple(a["shape"]))
                    for a in arrays
                )
            self.hits += len(out)
            self.misses += len(keys) - len(out)
            return out
        except Exception as e:
            logger.warning("Remote KV batch_get failed: %s", e)
            return {}


class KVOffloadManager:
    """Moves KV pages between HBM and the offload tiers.

    Engine integration points:
    - ``offload_page(page_hash, *payload)``: called when a hashed page
      is evicted from HBM (numpy arrays, already device_get; 2 arrays
      for full-precision pages, 4 for int8 pages with scales).
    - ``lookup_chain(hashes)``: longest prefix of page hashes available
      in host/remote tiers (after the in-HBM prefix match misses).
    - ``fetch(page_hash)``: payload for restoration (device_put done by
      the model runner, which owns the device arrays).

    ``kv_dtype`` is folded into every tier key (see _stable_key) so
    pods storing pages in different formats never alias.
    """

    def __init__(self, host_pool: Optional[HostKVPool] = None,
                 remote: Optional[RemoteKVClient] = None,
                 write_through_remote: bool = True,
                 kv_dtype: str = ""):
        self.host = host_pool or HostKVPool()
        self.remote = remote
        self.write_through_remote = write_through_remote
        self.kv_dtype = kv_dtype
        self.restored_pages = 0
        self.offloaded_pages = 0

    def _key(self, page_hash: PageHash) -> str:
        return _stable_key(page_hash, self.kv_dtype)

    def key_for(self, page_hash: PageHash) -> str:
        """Public tier key for a chain hash (handoff descriptors name
        shipped pages by these keys)."""
        return self._key(page_hash)

    def handoff_ready(self, page_hash: PageHash) -> Optional[bool]:
        """Is a shipped page reachable in some tier? True/False is
        definitive; None means the remote tier could not be probed
        (transient) — see RemoteKVClient.probe."""
        key = self._key(page_hash)
        if self.host.contains(key):
            return True
        if self.remote is None:
            return False
        return self.remote.probe(key)

    def chain_id(self, root_hash: PageHash) -> str:
        """Cluster-cache chain id for a page chain: the tier key of
        its ROOT page hash. Sent as X-KV-Chain on write-through so the
        managed cache groups a chain's pages for admission demand and
        whole-chain eviction (kvecon/cluster_cache.py)."""
        return self._key(root_hash)

    def offload_page(self, page_hash: PageHash,
                     *payload: np.ndarray,
                     chain: Optional[str] = None) -> None:
        key = self._key(page_hash)
        self.host.put(key, payload)
        self.offloaded_pages += 1
        if self.remote is not None and self.write_through_remote:
            self.remote.put(key, payload, chain=chain)

    def lookup_chain(self, hashes: List[PageHash]) -> int:
        """How many leading pages of *hashes* can be restored."""
        n = 0
        for page_hash in hashes:
            key = self._key(page_hash)
            if self.host.contains(key):
                n += 1
                continue
            if self.remote is not None and self.remote.contains(key):
                n += 1
                continue
            break
        return n

    def fetch(self, page_hash: PageHash) -> Optional[PagePayload]:
        key = self._key(page_hash)
        payload = self.host.get(key)
        if payload is not None:
            return payload
        if self.remote is not None:
            payload = self.remote.get(key)
            if payload is not None:
                # Promote to the host tier for future hits.
                self.host.put(key, payload)
                return payload
        return None

    def fetch_many(self, hashes: List[PageHash]) -> List[
            Optional[PagePayload]]:
        """Payloads for ``hashes``, order-aligned (None = miss). Host
        hits serve locally; ALL remote misses go out as one batch_get
        round trip, and fetched pages promote into the host tier."""
        keys = [self._key(h) for h in hashes]
        out: List[Optional[PagePayload]] = [
            self.host.get(k) for k in keys
        ]
        missing = [k for k, p in zip(keys, out) if p is None]
        if missing and self.remote is not None:
            fetched = self.remote.batch_get(missing)
            for i, key in enumerate(keys):
                if out[i] is None and key in fetched:
                    out[i] = fetched[key]
                    self.host.put(key, fetched[key])
        return out

    def stats(self) -> Dict[str, float]:
        total = self.host.hits + self.host.misses
        stats = {
            "host_pages": len(self.host),
            "host_bytes": self.host.used_bytes,
            "host_hit_rate": (self.host.hits / total) if total else 0.0,
            "offloaded_pages": self.offloaded_pages,
            "restored_pages": self.restored_pages,
        }
        if self.remote is not None:
            stats.update({
                "cluster_hits": self.remote.hits,
                "cluster_misses": self.remote.misses,
                "cluster_admissions": self.remote.admissions,
                "cluster_rejections": self.remote.rejections,
            })
        return stats
