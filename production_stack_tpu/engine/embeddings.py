"""Engine-side /v1/embeddings: pooled hidden states of the served model.

The reference's stack routes /v1/embeddings through the router to vLLM
pooling-model pods (src/vllm_router/routers/main_router.py:54-60,
services/request_service/request.py proxy path); the engine itself is
vLLM. Here the TPU engine serves the endpoint directly: a dense forward
(models.llama.encode) produces final-norm hidden states, pooled per
sequence and L2-normalized.

TPU shape discipline: inputs are padded to power-of-two token buckets
and a fixed batch width, so the embed step compiles once per bucket and
is cached by XLA thereafter (same strategy as the prefill buckets,
engine/model_runner.py).
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from production_stack_tpu.engine.config import ModelConfig
from production_stack_tpu.utils.log import init_logger

logger = init_logger(__name__)

_POOLING_MODES = ("last", "mean")


class Embedder:
    """Jitted, bucketed embedding runner over the serving weights."""

    def __init__(self, config: ModelConfig, params, max_len: int,
                 pooling: str = "last", batch_width: int = 8):
        if pooling not in _POOLING_MODES:
            raise ValueError(
                f"pooling must be one of {_POOLING_MODES}, got {pooling!r}"
            )
        if config.architecture not in ("llama", "mistral", "qwen2"):
            raise NotImplementedError(
                "embeddings are implemented for the llama family "
                f"(got architecture={config.architecture!r})"
            )
        from production_stack_tpu.engine.quantization import (
            has_quantized_leaves,
        )
        if has_quantized_leaves(params):
            raise NotImplementedError(
                "embeddings/score/rerank need unquantized weights "
                "(weight-only int8 is serving-path only)"
            )
        from production_stack_tpu.models import llama
        self.config = config
        self.params = params
        self.max_len = max_len
        self.pooling = pooling
        self.batch_width = batch_width
        self._encode = llama.encode

        def embed(params, tokens, lengths):
            hidden = self._encode(params, config, tokens)  # [B, T, H]
            t = tokens.shape[1]
            pos = jnp.arange(t)[None, :]
            mask = pos < lengths[:, None]  # [B, T]
            if pooling == "last":
                idx = jnp.maximum(lengths - 1, 0)
                pooled = hidden[jnp.arange(tokens.shape[0]), idx]
            else:
                m = mask[..., None].astype(hidden.dtype)
                pooled = (hidden * m).sum(axis=1) / jnp.maximum(
                    m.sum(axis=1), 1.0
                )
            pooled = pooled.astype(jnp.float32)
            norm = jnp.linalg.norm(pooled, axis=-1, keepdims=True)
            return pooled / jnp.maximum(norm, 1e-12)

        self._embed_jit = jax.jit(embed)
        # Multihost serving: host 0 publishes each embed chunk over the
        # step bridge so workers co-dispatch the same collective program
        # (parallel/distributed.py KIND_EMBED). None = single host.
        self.bridge = None

    def _bucket(self, n: int) -> int:
        b = 16
        while b < n:
            b *= 2
        return min(b, self.max_len)

    def _launch_chunk(self, tokens: np.ndarray,
                      lengths: np.ndarray) -> jax.Array:
        """Dispatch one padded embed program (async, unforced)."""
        return self._embed_jit(
            self.params, jnp.asarray(tokens), jnp.asarray(lengths)
        )

    def run_chunk(self, tokens: np.ndarray,
                  lengths: np.ndarray) -> np.ndarray:
        """One padded embed program (shared by host 0 and workers)."""
        return np.asarray(self._launch_chunk(tokens, lengths))

    def embed_batch(self, token_lists: List[List[int]]) -> np.ndarray:
        """Embed tokenized inputs; returns [N, hidden] float32."""
        out = np.zeros((len(token_lists), self.config.hidden_size),
                       np.float32)
        i = 0
        while i < len(token_lists):
            chunk = token_lists[i:i + self.batch_width]
            t = self._bucket(max(len(x) for x in chunk))
            b = self.batch_width
            tokens = np.zeros((b, t), np.int32)
            lengths = np.zeros((b,), np.int32)
            for j, ids in enumerate(chunk):
                ids = ids[:t]
                tokens[j, :len(ids)] = ids
                lengths[j] = len(ids)
            if self.bridge is not None:
                from production_stack_tpu.parallel.distributed import (
                    KIND_EMBED,
                )
                # Atomic publish+launch under the bridge lock so this
                # broadcast can't interleave with the engine thread's
                # prefill/decode header/payload pairs (and the local
                # program launches in published order). The blocking
                # host transfer happens after release so decode
                # dispatch isn't stalled for the embed forward.
                with self.bridge.lock:
                    self.bridge.publish(
                        KIND_EMBED, t,
                        {"tokens": tokens, "lengths": lengths},
                    )
                    pooled_dev = self._launch_chunk(tokens, lengths)
                pooled = np.asarray(pooled_dev)
            else:
                pooled = self.run_chunk(tokens, lengths)
            out[i:i + len(chunk)] = pooled[:len(chunk)]
            i += len(chunk)
        return out


def parse_embedding_input(raw, tokenizer,
                          max_len: Optional[int] = None
                          ) -> List[List[int]]:
    """OpenAI `input` field: str | [str] | [int] | [[int]] -> token lists."""
    if isinstance(raw, str):
        items = [raw]
    elif isinstance(raw, list) and raw and all(
            isinstance(x, int) for x in raw):
        items = [raw]
    elif isinstance(raw, list):
        items = raw
    else:
        raise ValueError("'input' must be a string, list of strings, "
                         "or token array(s)")
    token_lists: List[List[int]] = []
    for item in items:
        if isinstance(item, str):
            ids = tokenizer.encode(item)
        elif isinstance(item, list) and all(
                isinstance(x, int) for x in item):
            ids = list(item)
        else:
            raise ValueError("'input' entries must be strings or "
                             "integer token arrays")
        if not ids:
            raise ValueError("'input' entries must not be empty")
        if max_len is not None:
            ids = ids[:max_len]
        token_lists.append(ids)
    return token_lists
