"""Slow-request exemplar archive: metric -> trace with zero effort.

When a request breaches its SLO (obs/slo.py), the router pulls that
request's engine flight-recorder timeline (``/debug/trace/{id}``,
docs/observability.md) and archives the stitched router+engine
waterfall here. ``GET /debug/slow?class=&model=&limit=`` serves the
ring, newest first, so every p99 outlier on the dashboard links
straight to its per-request timeline; ``traceview
--from-slow-archive`` renders the same payload offline.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Deque, List, Optional


class SlowArchive:
    """Bounded ring of breach exemplars.

    An entry is a plain dict:
    ``{"request_id", "class", "model", "server", "ts", "breach":
    [{"metric", "value_s", "target_s"}], "spans": [router span dict,
    *engine span dicts], "waterfall": str}`` — ``spans`` is the
    stitched timeline, ``waterfall`` its rendered text.
    """

    def __init__(self, capacity: int = 64):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._ring: Deque[dict] = collections.deque(
            maxlen=self.capacity)
        self.archived_total = 0

    def add(self, entry: dict) -> None:
        entry.setdefault("ts", time.time())
        with self._lock:
            self._ring.append(entry)
            self.archived_total += 1

    def depth(self) -> int:
        with self._lock:
            return len(self._ring)

    def snapshot(self, priority_class: Optional[str] = None,
                 model: Optional[str] = None,
                 limit: int = 50) -> List[dict]:
        """Newest-first view, optionally filtered by class/model."""
        with self._lock:
            entries = list(self._ring)
        entries.reverse()
        if priority_class:
            entries = [e for e in entries
                       if e.get("class") == priority_class]
        if model:
            entries = [e for e in entries if e.get("model") == model]
        if limit >= 0:
            entries = entries[:limit]
        return entries
