"""Fleet rollup behind the router's ``GET /cluster/status``.

One JSON snapshot of the whole fleet, fed entirely from state the
router already keeps — the engine-stats scrape loop, service
discovery, the SLO ledger, the slow archive, and the drift sentinel.
No new polling: the handler is a pure fold over live singletons, and
``python -m production_stack_tpu.stacktop`` renders the result.

Inputs are passed in (not imported) so this module stays free of
router imports — the router imports ``obs``, never the reverse.
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, Optional


def _server_entry(stats, now: float) -> dict:
    """One per-server block from an EngineStats snapshot (accessed
    with getattr so older/partial snapshots degrade to defaults)."""
    g = lambda name, default=0.0: getattr(stats, name, default)  # noqa: E731
    summary_time = float(g("kv_summary_time"))
    return {
        "running": int(g("num_running_requests", 0)),
        "waiting": int(g("num_queuing_requests", 0)),
        "cache_usage": round(float(g("kv_usage_perc")), 4),
        "prefix_hit_rate": round(float(g("kv_cache_hit_rate")), 4),
        "draining": bool(g("engine_draining")),
        "kv": {
            "hot_chains": int(g("kv_summary_hot_chains")),
            "free_pages": int(g("kv_free_page_headroom")),
            "total_pages": int(g("kv_total_pages")),
            "summary_age_s": (round(now - summary_time, 3)
                              if summary_time > 0 else None),
        },
        "qos_shed": {k: int(v) for k, v in
                     sorted(g("qos_shed_by_class", {}).items())},
        "compile_events": {k: int(v) for k, v in
                           sorted(g("compile_events_by_kind",
                                    {}).items())},
        "mfu": round(float(g("engine_mfu")), 4),
        "hbm_bytes": {k: int(v) for k, v in
                      sorted(g("hbm_bytes_by_category", {}).items())},
        "step_time_median_s": {
            k: round(float(v), 6) for k, v in
            sorted(g("step_time_median_by_kind", {}).items())},
        # Self-tuning (docs/autotuning.md): controllers allowed to
        # act, latched guardrail freezes, and live knob values —
        # stacktop's AUTOTUNE column renders active count + a '!' on
        # any frozen controller.
        "autotune": {
            "active": int(g("autotune_active_controllers")),
            "frozen": {k: bool(v) for k, v in
                       sorted(g("autotune_frozen_by_controller",
                                {}).items())},
            "knobs": {k: round(float(v), 4) for k, v in
                      sorted(g("autotune_knob_by_controller",
                               {}).items())},
        },
        # Topology (docs/parallelism.md): the engine's mesh axis
        # sizes, which slice its devices sit on, and per-slice
        # liveness from the multihost bridge.
        "mesh": {
            "shape": {k: int(v) for k, v in
                      sorted(g("mesh_shape_by_axis", {}).items())},
            "slice_id": int(g("engine_slice_id")),
            "slices_live": {k: bool(v) for k, v in
                            sorted(g("slice_live_by_id",
                                     {}).items())},
        },
    }


def build_snapshot(engine_stats: Dict[str, object],
                   endpoints: Iterable[object] = (),
                   healthy: Optional[Dict[str, bool]] = None,
                   ledger=None, archive=None, sentinel=None,
                   rollout: Optional[dict] = None,
                   now: Optional[float] = None) -> dict:
    """The ``/cluster/status`` payload.

    ``engine_stats`` maps server URL -> EngineStats; ``endpoints`` are
    service-discovery EndpointInfo objects (for model/role metadata);
    ``healthy`` maps URL -> availability from the resilience layer;
    ``rollout`` is the fleet's per-pool rollout status relayed through
    the dynamic-config file (docs/fleet.md).
    """
    now = time.time() if now is None else now
    meta: Dict[str, dict] = {}
    for ep in endpoints:
        names = getattr(ep, "model_names", None) or ()
        meta[getattr(ep, "url", "")] = {
            "model": names[0] if names else None,
            "role": getattr(ep, "role", None),
        }
        revision = getattr(ep, "revision", "")
        if revision:
            meta[getattr(ep, "url", "")]["revision"] = revision
    servers: Dict[str, dict] = {}
    for url in sorted(set(engine_stats) | set(meta)):
        entry = _server_entry(
            engine_stats.get(url), now) if url in engine_stats else {}
        entry.update(meta.get(url, {}))
        if healthy is not None:
            entry["healthy"] = bool(healthy.get(url, True))
        servers[url] = entry
    snap: dict = {"ts": now, "servers": servers}
    if ledger is not None:
        snap["slo"] = ledger.snapshot()
    if sentinel is not None:
        medians = {url: getattr(stats, "step_time_median_by_kind", {})
                   for url, stats in engine_stats.items()}
        snap["perf_drift"] = sentinel.evaluate(medians)
    if archive is not None:
        snap["slow_archive"] = {"depth": archive.depth(),
                                "capacity": archive.capacity,
                                "archived_total":
                                    archive.archived_total}
    if rollout:
        snap["rollout"] = rollout
    return snap
