"""Cluster SLO ledger: declarative targets, attainment, burn rates.

The reference stack's observability stops at raw gauges; this module
answers the operator's actual question — "are we meeting SLO, for
whom?" — as first-class state. An :class:`SLOSpec` declares per
priority-class and per-model latency targets plus an objective
fraction; the router-side :class:`SLOLedger` classifies every
completed request as *good* or *bad* against its resolved target and
keeps a bounded event window from which it derives

- **attainment** per ``(class, model)`` — the good fraction over the
  trailing hour, exported as ``vllm:slo_attainment{class,model}``;
- **SRE multi-window burn rates** (5 m / 1 h) — how fast the error
  budget ``1 - objective`` is being consumed, exported as
  ``vllm:slo_burn_rate{window}``. A burn rate above 1.0 means the
  budget empties before the window does; the classic page-worthy
  signal is both windows burning hot at once.

All window arithmetic takes an injectable ``clock`` (the
``TokenBucket`` / ``PoolAutoscaler`` idiom) so tests drive it with a
fake clock. The `slo-contract` staticcheck rule keeps every spec
field below documented in docs/observability.md.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Tuple

# Burn-rate windows, label value -> seconds (SRE multi-window pattern:
# the short window catches fast burns, the long one filters blips).
BURN_WINDOWS: Dict[str, float] = {"5m": 300.0, "1h": 3600.0}

# Attainment is computed over the longest burn window.
ATTAINMENT_WINDOW_S = 3600.0


@dataclasses.dataclass
class SLOTarget:
    """Latency targets for one priority class or model; a target of 0
    disables that metric's check (same convention as the autoscaler
    knobs)."""

    ttft_s: float = 0.0
    itl_s: float = 0.0
    e2e_s: float = 0.0
    # Objective fraction override for this class/model; 0 inherits the
    # spec-level objective.
    objective: float = 0.0

    @classmethod
    def from_dict(cls, raw: dict) -> "SLOTarget":
        # An *explicit* objective must be a real fraction; only an
        # absent key means "inherit the spec-level objective".
        if "objective" in raw and not 0.0 < float(raw["objective"]) < 1.0:
            raise ValueError(
                "per-target objective must be in (0, 1), got "
                f"{raw['objective']}")
        return cls(
            ttft_s=float(raw.get("ttft_s", 0.0)),
            itl_s=float(raw.get("itl_s", 0.0)),
            e2e_s=float(raw.get("e2e_s", 0.0)),
            objective=float(raw.get("objective", 0.0)),
        )

    def merged_over(self, base: "SLOTarget") -> "SLOTarget":
        """Field-wise override: nonzero fields of ``self`` win over
        ``base`` (model overrides layered on the class target)."""
        return SLOTarget(
            ttft_s=self.ttft_s or base.ttft_s,
            itl_s=self.itl_s or base.itl_s,
            e2e_s=self.e2e_s or base.e2e_s,
            objective=self.objective or base.objective,
        )


@dataclasses.dataclass
class SLOSpec:
    """Declarative SLO spec, loaded from JSON via the router's
    ``--slo-spec`` flag. ``classes`` maps priority-class names
    (docs/qos.md) to targets; ``models`` maps model names to
    field-wise overrides layered on top of the class target."""

    objective: float = 0.99
    classes: Dict[str, SLOTarget] = dataclasses.field(
        default_factory=dict)
    models: Dict[str, SLOTarget] = dataclasses.field(
        default_factory=dict)

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {self.objective}")

    @classmethod
    def from_dict(cls, raw: dict) -> "SLOSpec":
        return cls(
            objective=float(raw.get("objective", 0.99)),
            classes={str(k): SLOTarget.from_dict(v or {})
                     for k, v in (raw.get("classes") or {}).items()},
            models={str(k): SLOTarget.from_dict(v or {})
                    for k, v in (raw.get("models") or {}).items()},
        )

    @classmethod
    def load(cls, path: str) -> "SLOSpec":
        with open(path) as fh:
            return cls.from_dict(json.load(fh))

    def resolve(self, priority_class: str,
                model: str) -> Tuple[SLOTarget, float]:
        """Effective (target, objective) for one request: the class
        target with any model override layered on, objective falling
        back to the spec default."""
        target = self.classes.get(priority_class, SLOTarget())
        override = self.models.get(model)
        if override is not None:
            target = override.merged_over(target)
        objective = target.objective or self.objective
        return target, objective


# One classified completion: (ts, class, model, server, good, budget)
# where budget is the request's allowed bad fraction (1 - objective).
_Event = Tuple[float, str, str, str, bool, float]


class SLOLedger:
    """Windowed good/bad classification per (class, model, server).

    Bounded: events older than the longest burn window are pruned on
    every observe, and the deque itself is capped as a backstop.
    """

    def __init__(self, spec: SLOSpec,
                 clock: Callable[[], float] = time.monotonic,
                 max_events: int = 65536):
        self.spec = spec
        self._clock = clock
        self._lock = threading.Lock()
        self._events: Deque[_Event] = collections.deque(
            maxlen=max_events)
        self.good_total: Dict[Tuple[str, str], int] = {}
        self.bad_total: Dict[Tuple[str, str], int] = {}

    # ---- classification --------------------------------------------------

    def observe(self, priority_class: str, model: str, server: str,
                ttft_s: Optional[float] = None,
                itl_s: Optional[float] = None,
                e2e_s: Optional[float] = None,
                now: Optional[float] = None) -> List[dict]:
        """Classify one completed request. Returns the breach list —
        empty when the request met its SLO — of
        ``{"metric", "value_s", "target_s"}`` dicts, which the caller
        uses to trigger slow-archive exemplar capture."""
        target, objective = self.spec.resolve(priority_class, model)
        breaches: List[dict] = []
        for metric, value, limit in (
                ("ttft", ttft_s, target.ttft_s),
                ("itl", itl_s, target.itl_s),
                ("e2e", e2e_s, target.e2e_s)):
            if limit > 0 and value is not None and value > limit:
                breaches.append({"metric": metric,
                                 "value_s": value,
                                 "target_s": limit})
        good = not breaches
        ts = self._clock() if now is None else now
        key = (priority_class, model)
        with self._lock:
            self._events.append(
                (ts, priority_class, model, server, good,
                 1.0 - objective))
            counts = self.good_total if good else self.bad_total
            counts[key] = counts.get(key, 0) + 1
            self._prune(ts)
        return breaches

    def _prune(self, now: float) -> None:
        horizon = now - max(BURN_WINDOWS.values())
        while self._events and self._events[0][0] < horizon:
            self._events.popleft()

    # ---- windowed views --------------------------------------------------

    def attainments(self, now: Optional[float] = None,
                    server: Optional[str] = None,
                    ) -> Dict[Tuple[str, str], float]:
        """Good fraction per (class, model) over the attainment
        window, optionally filtered to one server."""
        now = self._clock() if now is None else now
        horizon = now - ATTAINMENT_WINDOW_S
        good: Dict[Tuple[str, str], int] = {}
        total: Dict[Tuple[str, str], int] = {}
        with self._lock:
            for ts, cls, model, srv, ok, _budget in self._events:
                if ts < horizon:
                    continue
                if server is not None and srv != server:
                    continue
                key = (cls, model)
                total[key] = total.get(key, 0) + 1
                if ok:
                    good[key] = good.get(key, 0) + 1
        return {key: good.get(key, 0) / n
                for key, n in total.items() if n}

    def burn_rates(self, now: Optional[float] = None,
                   ) -> Dict[str, float]:
        """Error-budget burn per window: bad fraction divided by the
        traffic-weighted budget (mean per-request ``1 - objective``).
        0.0 with no traffic in the window; 1.0 means the budget
        empties exactly when the window does."""
        now = self._clock() if now is None else now
        out: Dict[str, float] = {}
        with self._lock:
            events = list(self._events)
        for label, width in BURN_WINDOWS.items():
            horizon = now - width
            n = bad = 0
            budget_sum = 0.0
            for ts, _cls, _model, _srv, ok, budget in events:
                if ts < horizon:
                    continue
                n += 1
                budget_sum += budget
                if not ok:
                    bad += 1
            if n == 0 or budget_sum <= 0:
                out[label] = 0.0
            else:
                out[label] = (bad / n) / (budget_sum / n)
        return out

    # ---- snapshots -------------------------------------------------------

    def totals(self) -> Dict[str, Dict[Tuple[str, str], int]]:
        with self._lock:
            return {"good": dict(self.good_total),
                    "bad": dict(self.bad_total)}

    def snapshot(self, now: Optional[float] = None) -> dict:
        """JSON-ready rollup for ``GET /cluster/status``."""
        now = self._clock() if now is None else now
        totals = self.totals()
        return {
            "objective": self.spec.objective,
            "attainment": {
                f"{cls}|{model}": round(frac, 6)
                for (cls, model), frac
                in sorted(self.attainments(now).items())},
            "burn_rate": {k: round(v, 6)
                          for k, v in self.burn_rates(now).items()},
            "good_requests": sum(totals["good"].values()),
            "bad_requests": sum(totals["bad"].values()),
        }
