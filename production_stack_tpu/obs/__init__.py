"""Cluster SLO ledger, slow-request exemplars, fleet rollup, drift.

The observability layer's answer to "are we meeting SLO, for whom,
and which requests are blowing it?" (docs/observability.md):

- :mod:`obs.slo` — declarative :class:`SLOSpec` (``--slo-spec``) and
  the windowed good/bad :class:`SLOLedger` with SRE multi-window
  burn rates.
- :mod:`obs.slow_archive` — bounded ring of SLO-breach exemplars,
  each holding the stitched router+engine waterfall
  (``GET /debug/slow``).
- :mod:`obs.cluster_status` — the ``GET /cluster/status`` fleet
  rollup that ``python -m production_stack_tpu.stacktop`` renders.
- :mod:`obs.drift` — the perf-drift sentinel over step-time medians
  vs a committed baseline (``vllm:perf_drift{phase}``).

The router installs live instances here at startup; the metrics
service and route handlers read them back. ``None`` means the
feature is off (no ``--slo-spec`` / ``--perf-baseline``), and every
consumer guards on it.
"""

from __future__ import annotations

from typing import Optional

from production_stack_tpu.obs.drift import DriftSentinel
from production_stack_tpu.obs.slo import (  # noqa: F401
    BURN_WINDOWS,
    SLOLedger,
    SLOSpec,
    SLOTarget,
)
from production_stack_tpu.obs.slow_archive import SlowArchive

_slo_ledger: Optional[SLOLedger] = None
_slow_archive: Optional[SlowArchive] = None
_drift_sentinel: Optional[DriftSentinel] = None


def install(ledger: Optional[SLOLedger] = None,
            archive: Optional[SlowArchive] = None,
            sentinel: Optional[DriftSentinel] = None) -> None:
    """Install (or clear, with None) the process-wide instances."""
    global _slo_ledger, _slow_archive, _drift_sentinel
    _slo_ledger = ledger
    _slow_archive = archive
    _drift_sentinel = sentinel


def get_slo_ledger() -> Optional[SLOLedger]:
    return _slo_ledger


def get_slow_archive() -> Optional[SlowArchive]:
    return _slow_archive


def get_drift_sentinel() -> Optional[DriftSentinel]:
    return _drift_sentinel
