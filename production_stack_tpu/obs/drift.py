"""Perf-drift sentinel: step-time medians vs a committed baseline.

The device performance observatory (engine/perf_observatory.py) keeps
a bounded ring of recent per-kind step durations and exports their
medians as ``vllm:engine_step_time_median_seconds{kind}``. This
sentinel compares the scraped medians against a committed baseline
file and flips ``vllm:perf_drift{phase}`` when any server's median
drifts beyond the band — turning silent regressions (the BENCH_r02
silent-XLA-fallback class) into an alertable gauge instead of a
number an operator derives by hand.

Baseline JSON (e.g. observability/perf_baseline.json)::

    {"band": 0.25, "phases": {"decode": 0.025, "prefill": 0.5}}

``band`` is the allowed relative deviation (0.25 = ±25 %); phases
absent from the baseline are never flagged.
"""

from __future__ import annotations

import json
from typing import Dict


class DriftSentinel:
    def __init__(self, phases: Dict[str, float], band: float = 0.25):
        self.phases = {str(k): float(v) for k, v in phases.items()
                       if float(v) > 0}
        self.band = float(band)
        if self.band <= 0:
            raise ValueError(f"band must be > 0, got {band}")

    @classmethod
    def load(cls, path: str) -> "DriftSentinel":
        with open(path) as fh:
            raw = json.load(fh)
        return cls(phases=raw.get("phases") or {},
                   band=float(raw.get("band", 0.25)))

    def evaluate(self, medians_by_server: Dict[str, Dict[str, float]],
                 ) -> Dict[str, dict]:
        """Per baseline phase: the worst observed median across
        servers, its relative drift, and whether the band tripped.
        Servers reporting no median for a phase (idle, no steps yet)
        contribute nothing — absence of data is not drift."""
        out: Dict[str, dict] = {}
        for phase, base in self.phases.items():
            worst_drift = 0.0
            worst_observed = None
            for medians in medians_by_server.values():
                observed = medians.get(phase)
                if observed is None or observed <= 0:
                    continue
                drift = abs(observed - base) / base
                if drift >= worst_drift:
                    worst_drift = drift
                    worst_observed = observed
            out[phase] = {
                "baseline_s": base,
                "observed_s": worst_observed,
                "drift": (round(worst_drift, 6)
                          if worst_observed is not None else None),
                "tripped": (worst_observed is not None
                            and worst_drift > self.band),
            }
        return out

    def flags(self, medians_by_server: Dict[str, Dict[str, float]],
              ) -> Dict[str, float]:
        """{phase: 0.0/1.0} — the ``vllm:perf_drift{phase}`` values."""
        return {phase: 1.0 if info["tripped"] else 0.0
                for phase, info in
                self.evaluate(medians_by_server).items()}
