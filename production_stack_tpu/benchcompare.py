"""Compare two bench.py JSON result files and flag regressions.

Usage::

    python -m production_stack_tpu.benchcompare old.json new.json \
        [--threshold 0.05]

Each input file holds the JSON lines (or a single object, or a JSON
array) printed by ``bench.py`` — objects of the shape
``{"metric": ..., "value": ..., "unit": ..., "extra": {...}}``. The
tool flattens every numeric field (including nested ``extra`` dicts
such as the device observatory's ``compile_events`` /
``hbm_bytes``) into dotted keys, classifies each key as
higher-is-better or lower-is-better by name, and compares the two
runs. Exit status is 0 when no metric regressed beyond the relative
threshold and 1 otherwise — suitable for CI gates around the
BENCH_* rounds.

Keys whose direction cannot be inferred (and non-numeric fields) are
reported as informational only and never fail the comparison.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

# Substring → direction heuristics, checked in order. The first
# matching fragment wins, so more specific fragments go first
# (``tokens_per_s`` must win over the lower-is-better ``_s``).
_HIGHER_BETTER = (
    "tok_s", "tokens_per_s", "tokens/s", "per_s", "req_per_s", "rate",
    "goodput", "mfu", "jain", "acceptance", "hit", "overlap",
    "capacity", "throughput",
    # --worker rollout: streams carried across revisions intact, and
    # a bad canary actually caught by the judge (docs/fleet.md).
    "rollout_migrated", "rollout_detected", "rollout_attainment",
    # unified A/B: the fused ragged kernel stayed resolved for the
    # unified step (0/1 shadow of attention_impl_unified — a
    # regression back to the composed path reads as a drop to 0).
    "ragged_kernel",
    # --worker scaleout: fraction-of-linear per-chip goodput as
    # replicas are added (docs/parallelism.md) — the goodput/tok_s
    # fragments above already classify the raw scaleout_goodput_*
    # keys; this covers the derived 1->N ratios.
    "linearity",
    # --worker drift: shadow mode's greedy output must stay
    # byte-identical to off (docs/autotuning.md) — a drop to 0 means
    # shadow perturbed a sampled token.
    "byte_identical",
)
_LOWER_BETTER = (
    "p50", "p90", "p99", "latency", "itl", "ttft", "seconds", "_ms",
    "_s", "pad_ratio", "compile_events", "queueing", "hbm_bytes",
    "shed", "preempt",
    # --worker rollout failure counters: client-visible errors and
    # streams broken mid-rollout should be zero.
    "rollout_5xx", "rollout_broken", "rollout_rollback",
    "rollout_alarm",
    # --worker drift: guardrail freezes during the scripted phases
    # mean the sentinel blamed the controllers for the workload.
    "frozen",
)


def classify(key: str) -> Optional[str]:
    """Return ``"higher"``, ``"lower"``, or None when unknown."""
    low = key.lower()
    for frag in _HIGHER_BETTER:
        if frag in low:
            return "higher"
    for frag in _LOWER_BETTER:
        if frag in low:
            return "lower"
    return None


def _flatten(prefix: str, value: Any, out: Dict[str, float]) -> None:
    if isinstance(value, bool):
        out[prefix] = 1.0 if value else 0.0
    elif isinstance(value, (int, float)):
        out[prefix] = float(value)
    elif isinstance(value, dict):
        for k, v in value.items():
            _flatten(f"{prefix}.{k}" if prefix else str(k), v, out)


def _records(text: str) -> List[Dict[str, Any]]:
    """Parse a bench results file: a JSON array, a single object, or
    one JSON object per line (bench.py's native output)."""
    text = text.strip()
    if not text:
        return []
    try:
        data = json.loads(text)
    except ValueError:
        data = [json.loads(line) for line in text.splitlines()
                if line.strip()]
    if isinstance(data, dict):
        data = [data]
    return [rec for rec in data if isinstance(rec, dict)]


def load_metrics(path: str) -> Dict[str, float]:
    with open(path) as fh:
        records = _records(fh.read())
    out: Dict[str, float] = {}
    for rec in records:
        name = str(rec.get("metric", "bench"))
        # Fold the unit into the key so direction classification sees
        # it ("req/s" -> ".value.req_per_s" -> higher-is-better).
        unit = str(rec.get("unit", "")).replace("/", "_per_")
        key = f"{name}.value.{unit}" if unit else f"{name}.value"
        _flatten(key, rec.get("value"), out)
        _flatten(name, rec.get("extra", {}), out)
    return out


def compare(old: Dict[str, float], new: Dict[str, float],
            threshold: float) -> Tuple[List[str], List[str]]:
    """Return (report_lines, regression_lines)."""
    lines: List[str] = []
    regressions: List[str] = []
    for key in sorted(set(old) & set(new)):
        before, after = old[key], new[key]
        direction = classify(key)
        if before == after:
            delta = 0.0
        elif before == 0:
            delta = float("inf") if after > 0 else float("-inf")
        else:
            delta = (after - before) / abs(before)
        regressed = False
        if direction == "higher":
            regressed = delta < -threshold
        elif direction == "lower":
            regressed = delta > threshold
        tag = ("?" if direction is None
               else "REGRESSION" if regressed else "ok")
        line = (f"{key}: {before:g} -> {after:g} "
                f"({delta:+.1%}) [{tag}]")
        lines.append(line)
        if regressed:
            regressions.append(line)
    for key in sorted(set(old) - set(new)):
        lines.append(f"{key}: {old[key]:g} -> (missing)")
    for key in sorted(set(new) - set(old)):
        lines.append(f"{key}: (new) -> {new[key]:g}")
    return lines, regressions


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m production_stack_tpu.benchcompare",
        description="Compare two bench.py JSON outputs; exit 1 when "
                    "any direction-classified metric regresses beyond "
                    "the relative threshold.")
    parser.add_argument("old", help="baseline bench JSON file")
    parser.add_argument("new", help="candidate bench JSON file")
    parser.add_argument("--threshold", type=float, default=0.05,
                        help="relative regression tolerance "
                             "(default 0.05 = 5%%)")
    args = parser.parse_args(argv)

    old = load_metrics(args.old)
    new = load_metrics(args.new)
    if not old or not new:
        print("benchcompare: no numeric metrics found "
              f"(old={len(old)}, new={len(new)})", file=sys.stderr)
        return 2
    lines, regressions = compare(old, new, args.threshold)
    for line in lines:
        print(line)
    if regressions:
        print(f"\n{len(regressions)} regression(s) beyond "
              f"{args.threshold:.0%}:", file=sys.stderr)
        for line in regressions:
            print(f"  {line}", file=sys.stderr)
        return 1
    print("\nno regressions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
